"""The flight recorder: a bounded in-memory ring of recent telemetry,
dumped atomically when something goes wrong.

Post-mortems used to require foresight: unless a run was started with
``--obs-log``, a quarantine or a watchdog trip left nothing to read.  The
flight recorder inverts that: when armed, every obs event and causal span
is *also* appended to a bounded per-subsystem ring (steady-state cost: one
deque append — no I/O, no serialization), and the interesting triggers —
session **quarantine**, **park**, dispatch-deadline **watchdog** trips,
degradation-ladder **step-ups**, numerics **sentinel** trips and injected
**ChaosCrash** deaths — dump the ring to disk through
:func:`disco_tpu.io.atomic.atomic_write`, so the final dump path is either
complete or absent (the repo-wide crash-safety invariant).

Dumps are **byte-stable**: the JSON payload is a pure function of the ring
contents (sorted keys, fixed separators), so dumping the same state twice
yields identical bytes — what lets ``make scope-check`` pin a dump against
a re-dump, and what makes dumps diffable across post-mortems.

Like the :class:`~disco_tpu.obs.events.Recorder` and the
:class:`~disco_tpu.obs.trace.Tracer`, the process-global
:class:`FlightRecorder` is a strict no-op while disabled (one attribute
check), and no flight failure may ever break the pipeline it observes:
:func:`auto_dump` swallows I/O errors into a counter.

No reference counterpart: the reference has no observability at all
(SURVEY.md §5.1); the design is the standard black-box/flight-recorder
pattern of long-lived serving stacks, sized down to a dependency-free ring
+ JSON dump.
"""
from __future__ import annotations

import collections
import json
import threading
import time

from disco_tpu.obs import events as _events
from disco_tpu.obs import metrics as _metrics

#: Default per-subsystem ring depth (entries, not bytes).
DEFAULT_CAPACITY = 256

#: The dump triggers wired through the stack (documentation + the
#: ``flight`` event's ``trigger`` attr; runtime stays permissive so tests
#: can dump under synthetic triggers).
TRIGGERS = frozenset(
    {
        "quarantine",    # serve/scheduler.py: transport budget exhausted
        "park",          # serve/scheduler.py: session parked
        "watchdog",      # serve/scheduler.py: tick blew its dispatch deadline
        "ladder_step_up",  # serve/ladder.py: the overload controller degraded
        "sentinel",      # obs/sentinels.py: non-finite tensor detected
        "chaos_crash",   # runs/chaos.py: injected in-process death
        "demotion",      # promote/controller.py: canary gate failed, rollback issued
        "manual",        # explicit dump() calls (CLI / tests)
    }
)


class FlightRecorder:
    """Bounded per-subsystem rings + atomic dump-on-trigger.

    ``enable(dump_dir=...)`` arms collection; events flow in through
    :meth:`add` (the obs recorder fans every event here — see
    ``events.Recorder.record``) keyed by their stage (falling back to the
    kind), each ring bounded at ``capacity``.  :meth:`dump` serializes a
    deterministic snapshot through ``io.atomic``; :meth:`auto_dump` is the
    trigger-site entry point — a no-op unless armed *with* a dump dir, and
    exception-free by contract.
    """

    def __init__(self):
        self.enabled = False
        self.dump_dir = None
        self.capacity = DEFAULT_CAPACITY
        self._rings: dict = {}
        self._lock = threading.Lock()
        self._dump_seq = 0
        self.entries_added = 0
        self.dumps_written = 0

    def enable(self, dump_dir=None, capacity: int = DEFAULT_CAPACITY) -> None:
        from pathlib import Path

        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        with self._lock:
            self._rings.clear()
            self.capacity = capacity
            self.dump_dir = Path(dump_dir) if dump_dir is not None else None
            self._dump_seq = 0
            self.enabled = True
        _events.refresh_sinks()

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self._rings.clear()
            self.dump_dir = None
        _events.refresh_sinks()

    # -- collection (hot path) -----------------------------------------------
    def add(self, subsystem: str, kind: str, attrs: dict,
            t_wall: float | None = None) -> None:
        """Append one entry to a subsystem's ring (bounded: the deque drops
        the oldest).  Called by the obs recorder for every event while
        armed; safe from any thread.  ``flight`` events themselves are NOT
        collected — a ring that ingests its own dump notices would never
        dump the same bytes twice (the byte-stability contract)."""
        if not self.enabled or kind == "flight":
            return
        entry = {"t": time.time() if t_wall is None else t_wall,
                 "kind": kind, "attrs": attrs}
        with self._lock:
            ring = self._rings.get(subsystem)
            if ring is None:
                ring = self._rings[subsystem] = collections.deque(
                    maxlen=self.capacity)
            ring.append(entry)
            self.entries_added += 1

    # -- snapshot / dump -----------------------------------------------------
    def snapshot(self) -> dict:
        """{subsystem: [entry, ...]} — oldest first, a deep-enough copy that
        a dump cannot race later appends."""
        with self._lock:
            return {k: [dict(e) for e in ring] for k, ring in self._rings.items()}

    def dump_bytes(self, trigger: str, reason: str | None = None,
                   snapshot: dict | None = None) -> bytes:
        """The deterministic dump payload: a pure function of the ring
        contents (sorted keys, fixed separators) — dumping unchanged state
        twice yields identical bytes (the byte-stability scope-check pins).
        ``snapshot``: reuse an already-taken :meth:`snapshot` (the dump
        path takes exactly one, so the written bytes and the dump notice
        can never disagree)."""
        payload = {
            "flight_recorder": 1,
            "trigger": trigger,
            "reason": reason,
            "capacity": self.capacity,
            "entries_added": self.entries_added,
            "subsystems": self.snapshot() if snapshot is None else snapshot,
        }
        return (json.dumps(payload, sort_keys=True, default=_events._jsonable,
                           separators=(",", ":")) + "\n").encode()

    def dump(self, path=None, *, trigger: str = "manual",
             reason: str | None = None):
        """Write the ring snapshot atomically; returns the final path (or
        None when no path could be derived).  ``path`` defaults to
        ``<dump_dir>/flight-<seq:04d>-<trigger>.json`` — the sequence
        number keeps repeated triggers from overwriting each other."""
        from pathlib import Path

        from disco_tpu.io.atomic import atomic_write

        if path is None:
            if self.dump_dir is None:
                return None
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            path = Path(self.dump_dir) / f"flight-{seq:04d}-{trigger}.json"
        snap = self.snapshot()
        data = self.dump_bytes(trigger, reason, snapshot=snap)
        path = Path(path)
        with atomic_write(path) as fh:
            fh.write(data)
        with self._lock:
            # any role's trigger site may dump (scheduler quarantine on the
            # dispatch thread, chaos seams anywhere, manual CLI calls):
            # the bump shares the ring lock like every other counter here
            self.dumps_written += 1
        _metrics.REGISTRY.counter("flight_dumps").inc()
        _events.record("flight", stage=None, trigger=trigger, reason=reason,
                       path=str(path),
                       n_entries=sum(len(v) for v in snap.values()))
        return path

    def auto_dump(self, trigger: str, reason: str | None = None):
        """The trigger-site seam: dump if armed with a dump dir, swallow
        any failure into ``flight_dump_errors`` — a post-mortem aid must
        never break the pipeline it observes (obs package contract)."""
        if not self.enabled or self.dump_dir is None:
            return None
        try:
            return self.dump(trigger=trigger, reason=reason)
        except BaseException as e:  # ChaosCrash included: a dump during a
            # simulated death must not mask the death itself
            from disco_tpu.runs.chaos import ChaosCrash

            if isinstance(e, ChaosCrash):
                raise
            _metrics.REGISTRY.counter("flight_dump_errors").inc()
            return None


_FLIGHT = FlightRecorder()


def flight() -> FlightRecorder:
    """The process-global :class:`FlightRecorder`.

    No reference counterpart (module docstring)."""
    return _FLIGHT


def enabled() -> bool:
    """True while the flight recorder is collecting.

    No reference counterpart (module docstring)."""
    return _FLIGHT.enabled


def enable(dump_dir=None, capacity: int = DEFAULT_CAPACITY) -> None:
    """Arm the process-global flight recorder (``disco-serve
    --flight-dir``, the scope-check gate).

    No reference counterpart (module docstring)."""
    _FLIGHT.enable(dump_dir=dump_dir, capacity=capacity)


def disable() -> None:
    """Disarm and clear the process-global flight recorder.

    No reference counterpart (module docstring)."""
    _FLIGHT.disable()


def auto_dump(trigger: str, reason: str | None = None):
    """Module-level :meth:`FlightRecorder.auto_dump` on the process-global
    recorder — the one-liner the trigger sites call.

    No reference counterpart (module docstring)."""
    return _FLIGHT.auto_dump(trigger, reason=reason)


def dump(path=None, *, trigger: str = "manual", reason: str | None = None):
    """Module-level :meth:`FlightRecorder.dump` on the process-global
    recorder.

    No reference counterpart (module docstring)."""
    return _FLIGHT.dump(path, trigger=trigger, reason=reason)
