"""disco_tpu.obs — structured run telemetry for the enhancement stack.

The reference has no observability at all (SURVEY.md §5.1: ad-hoc
``time.clock()`` prints in train.py are its only instrumentation).  This
package is the rebuild's answer, sized for the Axon-tunnel reality that
*dispatch/fence counting* — not wall-clock — is the load-bearing signal
(every fenced dispatch costs a fixed ~80 ms RPC and ``block_until_ready``
returns without waiting, CLAUDE.md):

* :mod:`disco_tpu.obs.events`     — append-only JSONL event log with a
  process-global :class:`~disco_tpu.obs.events.Recorder` (strict no-op when
  disabled) and a run-manifest emitter (git SHA, backend, devices, config,
  package versions).
* :mod:`disco_tpu.obs.metrics`    — counters / gauges / histograms registry
  with ``snapshot()`` and a pretty-printer; home of :class:`StageTimer` and
  :func:`trace_to` (moved from ``utils.profiling``, which re-exports them).
* :mod:`disco_tpu.obs.accounting` — fence/RPC accounting around
  ``milestones._fence`` and a recompile counter via :func:`counted_jit`.
* :mod:`disco_tpu.obs.sentinels`  — opt-in numerics watchdogs
  (:func:`check_finite`) at stage boundaries that record the offending
  stage + tensor stats instead of silently propagating NaNs.
* :mod:`disco_tpu.obs.trace`      — causal tracing: a
  trace/span/parent triple minted at client block submission and advanced
  hop by hop (enqueue → dispatch → readback → deliver → tap →
  train_batch), recorded as ``span`` events and rendered by ``disco-obs
  trace`` as a per-hop waterfall.  Strict no-op while disabled.
* :mod:`disco_tpu.obs.flight`     — the flight recorder: a bounded
  in-memory ring of recent events/spans per subsystem, dumped atomically
  (byte-stable JSON) on quarantine, park, watchdog, ladder step-up,
  sentinel trip or ChaosCrash — post-mortems without foresight.
* :mod:`disco_tpu.obs.scope`      — the ``make scope-check`` gate: full
  causal chains for every delivered serve frame, byte-stable flight dumps
  on an injected fault, and a ``status`` frame consistent with the
  counters registry.

Consumers: ``enhance/driver.py`` and ``enhance/streaming.py`` (per-stage
events, per-clip counters), ``nn/training.py`` (per-epoch events),
``bench.py --obs-log`` (sideband event stream), and ``cli/obs.py``
(``report`` / ``compare`` renderers).

Everything here must be safe to call unconditionally from hot paths: with
recording disabled (the default) every entry point returns after one
attribute check, and no obs failure may ever break the pipeline it observes.
"""
from disco_tpu.obs.events import (
    Event,
    Recorder,
    disable,
    enable,
    enabled,
    read_events,
    record,
    recorder,
    recording,
    stage,
    validate_event,
    write_manifest,
)
from disco_tpu.obs import flight, trace
from disco_tpu.obs.metrics import REGISTRY, StageTimer, trace_to
from disco_tpu.obs.accounting import (
    counted_jit,
    fence_count,
    fence_tick,
    recompile_count,
    rpc_overhead_s,
)
from disco_tpu.obs.sentinels import check_finite

__all__ = [
    "Event",
    "Recorder",
    "REGISTRY",
    "StageTimer",
    "check_finite",
    "counted_jit",
    "disable",
    "enable",
    "enabled",
    "fence_count",
    "fence_tick",
    "flight",
    "read_events",
    "recompile_count",
    "record",
    "recorder",
    "recording",
    "rpc_overhead_s",
    "stage",
    "trace",
    "trace_to",
    "validate_event",
    "write_manifest",
]
