"""Append-only JSONL event log (the telemetry backbone of the obs package).

Every event is one JSON object per line::

    {"t": <unix wall time>, "kind": <str>, "stage": <str|null>, "attrs": {...}}

``kind`` is one of :data:`EVENT_KINDS`; ``stage`` names the pipeline stage
the event describes (null for run-scoped events like the manifest).  The log
is a *sideband*: nothing here ever writes to stdout (``bench.py``'s
ONE-JSON-line stdout contract must survive with recording enabled), and the
process-global :class:`Recorder` is a strict no-op while disabled — one
attribute check and return, so the default pipeline pays nothing.

No reference counterpart: the reference has no event log of any kind
(SURVEY.md §5.1); the schema follows the structured-trace convention of
production JAX stacks (jax.profiler trace events, Prometheus-style
registries) sized down to a dependency-free JSONL file.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import sys
import threading
import time
from pathlib import Path

#: The closed set of event kinds ``cli/obs.py report`` and the schema test
#: understand.  Extend deliberately — ``make obs-check`` pins this schema.
EVENT_KINDS = frozenset(
    {
        "manifest",     # run header: git SHA, backend, devices, config, versions
        "stage_end",    # a timed pipeline stage finished (attrs: dur_s, fences, ...)
        "clip",         # one clip/RIR fully enhanced + persisted
        "epoch",        # one training epoch (attrs: train_loss, val_loss, steps)
        "jit_trace",    # a counted_jit entry point (re)compiled
        "sentinel",     # numerics watchdog tripped (attrs: tensor stats)
        "counters",     # metrics-registry snapshot (usually last event of a run)
        "watchdog",     # bench watchdog fired (no-progress diagnostic)
        "bench_result", # the full bench record, mirrored off stdout
        "fault",        # an injected or detected fault (attrs: fault, node, ...)
        "recovery",     # a retried operation succeeded (utils.resilience)
        "degraded",     # the pipeline entered degraded mode (excluded streams)
        "run_start",    # a crash-safe run began (attrs: preflight, ledger, ...)
        "run_resume",   # a run resumed from its ledger (attrs: done/requeued counts)
        "session",      # serve session lifecycle (attrs: action=open/close/evict/drain)
        "tap",          # flywheel corpus-tap lifecycle (attrs: action=shard/close)
        "interrupted",  # graceful stop requested (SIGTERM/SIGINT; runs.interrupt)
        "warning",      # degraded input / requeued unit — visible, non-fatal
        "span",         # one causal-trace hop (obs.trace; attrs: trace/span/parent)
        "flight",       # a flight-recorder dump landed (obs.flight; attrs: trigger/path)
        "promotion",    # a weight generation staged/adopted/promoted (promote/)
        "canary",       # canary window lifecycle (attrs: action=assign/score/window)
        "rollback",     # a demoted candidate rolled back (attrs: reason, failing metric)
        "generation",   # resident trainer published a generation (flywheel/resident)
        "train_throttled",  # ladder rung paused/resumed resident training
        "scene",        # one simulated scene batch (scenes/; attrs: epoch, index, n_scenes)
        "note",         # freeform annotation
    }
)


@dataclasses.dataclass(frozen=True)
class Event:
    """One telemetry event (the in-memory twin of a JSONL line)."""

    kind: str
    stage: str | None
    t_wall: float
    attrs: dict

    def to_json(self) -> str:
        return json.dumps(
            {"t": self.t_wall, "kind": self.kind, "stage": self.stage, "attrs": self.attrs},
            default=_jsonable,
        )


def _jsonable(x):
    """Last-resort JSON coercion: numpy scalars -> python, else repr.  An
    unserializable attr must degrade to a string, never raise — recording can
    be called from exception handlers and watchdog threads."""
    if hasattr(x, "item"):
        try:
            return x.item()
        except Exception:
            pass
    return repr(x)


class Recorder:
    """Process-global JSONL event sink.

    Strict no-op while inactive: :meth:`record` returns after a single
    attribute check (``_active`` folds the JSONL sink and the flight-ring
    sink into one flag — see :func:`refresh_sinks`).  When enabled, lines
    are appended and flushed per event (the watchdog path calls
    ``os._exit`` right after recording), behind a lock (the batched driver
    scores on a thread pool; the bench watchdog is a daemon thread).

    **Rotation**: ``enable(path, max_bytes=N)`` bounds the live file — once
    an append pushes it past ``N`` bytes the file is atomically renamed to
    the next numbered segment (``events.jsonl`` → ``events.1.jsonl``,
    ``events.2.jsonl``, ...; ``os.replace``, so a crash never leaves a
    half-rotated log) and a fresh live file is opened.  :func:`read_events`
    transparently spans the rotated segments in order, tolerating a torn
    final line at each rotation seam (a crash mid-append before the next
    process rotated) — long soak/serve runs no longer grow one file
    without bound.
    """

    def __init__(self):
        self.enabled = False
        self.path: Path | None = None
        self.max_bytes: int | None = None
        self.rotations = 0
        self._fh = None
        self._lock = threading.Lock()
        #: the armed FlightRecorder (obs.flight), or None — events fan out
        #: to its ring even when the JSONL sink is off
        self._flight = None
        self._active = False

    def _refresh_active(self) -> None:
        fl = self._flight
        self._active = self.enabled or (fl is not None and fl.enabled)

    def enable(self, path, max_bytes: int | None = None) -> None:
        if max_bytes is not None and int(max_bytes) < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self.path = Path(path)
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a")
            self.max_bytes = int(max_bytes) if max_bytes is not None else None
            self.rotations = 0
            self.enabled = True
            self._refresh_active()

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self.path = None
            self.max_bytes = None
            self._refresh_active()

    def record(self, kind: str, stage: str | None = None, **attrs) -> Event | None:
        if not self._active:
            return None
        ev = Event(kind=kind, stage=stage, t_wall=time.time(), attrs=attrs)
        fl = self._flight
        if fl is not None:
            fl.add(stage or kind, kind, attrs, ev.t_wall)
        if not self.enabled:
            return ev
        with self._lock:
            if self._fh is None:  # disabled between the check and the lock
                return None
            self._fh.write(ev.to_json() + "\n")
            self._fh.flush()
            if self.max_bytes is not None and self._fh.tell() >= self.max_bytes:
                self._rotate_locked()
        return ev

    def _rotate_locked(self) -> None:
        """Roll the live file over to the next numbered segment (caller
        holds the lock).  The rename is atomic; the live path is reopened
        fresh, so every line lives in exactly one segment."""
        self._fh.close()
        n = self.rotations + 1
        while True:  # a re-enabled path may already have older segments
            target = _segment_path(self.path, n)
            if not target.exists():
                break
            n += 1
        os.replace(self.path, target)
        self.rotations = n
        self._fh = open(self.path, "a")


_RECORDER = Recorder()


def recorder() -> Recorder:
    """The process-global :class:`Recorder`."""
    return _RECORDER


def enabled() -> bool:
    """True while the process-global recorder is recording."""
    return _RECORDER.enabled


def active() -> bool:
    """True while ANY event sink is live: the JSONL recorder OR the
    flight-recorder ring (the flag :meth:`Recorder.record` gates on).
    Opt-in instrumentation that should run in post-mortem-only mode — the
    numerics sentinels under ``--flight-dir`` without ``--obs-log`` —
    gates on this, not :func:`enabled`."""
    return _RECORDER._active


def enable(path, max_bytes: int | None = None) -> None:
    """Start recording to ``path`` (JSONL, append).  ``max_bytes`` arms
    size-bounded rotation (see :class:`Recorder`)."""
    _RECORDER.enable(path, max_bytes=max_bytes)


def refresh_sinks() -> None:
    """Re-derive the recorder's one-check activity flag from its sinks
    (called by ``obs.flight`` enable/disable — the flight ring receives
    events even while the JSONL sink is off, without adding a second check
    to the disabled hot path)."""
    from disco_tpu.obs import flight as _flight_mod

    fl = _flight_mod.flight()
    _RECORDER._flight = fl if fl.enabled else None
    _RECORDER._refresh_active()


def disable() -> None:
    """Stop recording on the process-global recorder and close the log."""
    _RECORDER.disable()


def record(kind: str, stage: str | None = None, **attrs) -> Event | None:
    """Record one event on the process-global recorder (no-op when disabled)."""
    return _RECORDER.record(kind, stage=stage, **attrs)


@contextlib.contextmanager
def recording(path, max_bytes: int | None = None):
    """Scoped recording: enable for the block, disable after (test helper and
    the CLI wiring — guarantees the file handle is released)."""
    enable(path, max_bytes=max_bytes)
    try:
        yield _RECORDER
    finally:
        disable()


@contextlib.contextmanager
def stage(name: str, **attrs):
    """Time a pipeline stage and record a ``stage_end`` event with its
    duration and the fence-count delta across the block.

    Disabled fast path: plain ``yield`` — no clock read, no dict build.
    The fence delta attributes tunnel RPCs to the stage that paid them
    (on the Axon attachment each fence is a fixed ~80 ms round-trip, so the
    *count* is the cost model — see ``obs.accounting``).
    """
    if not _RECORDER._active:
        yield
        return
    from disco_tpu.obs import accounting

    # Per-thread fence delta: the batched driver runs stages concurrently on
    # scoring workers; the process-wide count would cross-attribute fences.
    f0 = accounting.fence_count_thread()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        # measured keys win over caller attrs (never crash on a collision)
        record(
            "stage_end",
            stage=name,
            **{**attrs,
               "dur_s": round(dur, 6),
               "fences": accounting.fence_count_thread() - f0},
        )


def _git_sha() -> str | None:
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=Path(__file__).resolve().parents[2],
            capture_output=True,
            text=True,
            timeout=10,
        )
        return out.stdout.strip() or None
    except Exception:
        return None


def _versions() -> dict:
    from importlib import metadata

    vers = {"python": sys.version.split()[0]}
    for pkg in ("jax", "jaxlib", "flax", "optax", "numpy", "scipy"):
        try:
            vers[pkg] = metadata.version(pkg)
        except Exception:
            vers[pkg] = None
    return vers


def write_manifest(config: dict | None = None, **extra) -> Event | None:
    """Record the run manifest: git SHA, JAX backend/platform, device count
    and kind, the run's config dict, and package versions.

    Called once at driver/CLI startup (after ``enable``).  Every field is
    individually guarded — a broken git checkout or an uninitialised backend
    must degrade to nulls, never break the run being observed.
    """
    if not _RECORDER.enabled:
        return None
    platform = device_count = device_kind = None
    try:
        import jax

        devs = jax.devices()
        platform = devs[0].platform
        device_count = len(devs)
        device_kind = devs[0].device_kind
    except Exception:
        pass
    return record(
        "manifest",
        git_sha=_git_sha(),
        platform=platform,
        device_count=device_count,
        device_kind=device_kind,
        argv=list(sys.argv),
        cwd=os.getcwd(),
        config=config or {},
        versions=_versions(),
        **extra,
    )


def validate_event(d: dict) -> None:
    """Raise ``ValueError`` if ``d`` is not a schema-conforming event dict.
    ``make obs-check`` runs the test built on this, so schema drift fails CI."""
    for key in ("t", "kind", "stage", "attrs"):
        if key not in d:
            raise ValueError(f"event missing key {key!r}: {d}")
    if not isinstance(d["t"], (int, float)):
        raise ValueError(f"event 't' must be a number, got {d['t']!r}")
    if d["kind"] not in EVENT_KINDS:
        raise ValueError(f"unknown event kind {d['kind']!r} (known: {sorted(EVENT_KINDS)})")
    if d["stage"] is not None and not isinstance(d["stage"], str):
        raise ValueError(f"event 'stage' must be a string or null, got {d['stage']!r}")
    if not isinstance(d["attrs"], dict):
        raise ValueError(f"event 'attrs' must be an object, got {d['attrs']!r}")


def _segment_path(path: Path, n: int) -> Path:
    """Rotated-segment path ``n`` of a live log (``events.jsonl`` →
    ``events.1.jsonl``)."""
    return path.with_name(f"{path.stem}.{n}{path.suffix}")


def rotated_segments(path) -> list[Path]:
    """The live log's rotated segments, oldest first (``events.1.jsonl``
    before ``events.2.jsonl``).  Pure discovery — missing segments are
    simply absent (a cleaned-up tail is legal)."""
    path = Path(path)
    prefix, suffix = path.stem + ".", path.suffix
    found = []
    for p in path.parent.glob(f"{path.stem}.*{path.suffix}"):
        mid = p.name[len(prefix):len(p.name) - len(suffix)] if suffix else \
            p.name[len(prefix):]
        if mid.isdigit():
            found.append((int(mid), p))
    return [p for _n, p in sorted(found)]


def _read_one(path, validate: bool, tolerate_torn_tail: bool) -> list[dict]:
    """One file's events.  ``tolerate_torn_tail`` skips a final line that is
    not valid JSON — the rotation-seam tear (a crash mid-append whose file
    was later rotated); a bad line anywhere ELSE still raises, and schema
    violations always raise."""
    with open(path) as fh:
        raw = [(i, ln.strip()) for i, ln in enumerate(fh, 1)]
    raw = [(i, ln) for i, ln in raw if ln]
    events = []
    for pos, (lineno, line) in enumerate(raw):
        try:
            d = json.loads(line)
        except json.JSONDecodeError as e:
            if tolerate_torn_tail and pos == len(raw) - 1:
                break  # the torn final line of a rotated segment
            raise ValueError(f"{path}:{lineno}: not valid JSON: {e}") from None
        if validate:
            try:
                validate_event(d)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: {e}") from None
        events.append(d)
    return events


def read_events(path, validate: bool = True) -> list[dict]:
    """Load a JSONL event log (the ``cli/obs.py report`` input), spanning
    any rotated segments (``events.1.jsonl``, ``events.2.jsonl``, ...,
    oldest first, then the live file).  A torn final line at a rotation
    seam is skipped — the crash-mid-append shape rotation can strand —
    while any other malformed line still raises."""
    segments = rotated_segments(path)
    events = []
    for seg in segments:
        events.extend(_read_one(seg, validate, tolerate_torn_tail=True))
    events.extend(_read_one(path, validate, tolerate_torn_tail=False))
    return events
