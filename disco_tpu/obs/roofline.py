"""The roofline join: measured stage times × modeled stage costs.

``disco-obs roofline`` merges a bench record's measured ``stage_ms``
(on-device, k-queued slope — bench.py) with the analytic per-stage costs
of :mod:`disco_tpu.analysis.meter.stages` re-traced at the record's
workload, and renders per stage: achieved FLOP/s, achieved HBM GB/s,
fraction of the declared hardware peaks, and a verdict —

* **compute-bound** — the stage's modeled flops at peak throughput take
  longer than its modeled bytes at peak bandwidth, and the measured time
  is within sight of the compute roof;
* **bandwidth-bound** — the modeled bytes dominate;
* **dispatch-bound** — the measured time is so far above BOTH roofs
  (below ``dispatch_frac`` of peak on the binding dimension) that
  neither resource explains it: launch/dispatch overhead does.

The join is deliberately hermetic: the record supplies every measured
number, the cost model supplies every modeled one, and tracing is
abstract — a roofline over an on-TPU record renders on a laptop with no
TPU attached.  When a record predates the ``workload`` field
(BENCH_r01–r05) the bench headline defaults are assumed and the table
says so.

No reference counterpart: the reference repo has no cost model and no
benchmarks (SURVEY.md §5.1).
"""
from __future__ import annotations

#: default hardware peaks the verdict is judged against — TPU v5e dense
#: f32 MXU peak and HBM bandwidth (the attached testbed; override with
#: ``--peak-tflops`` / ``--peak-gbps`` for other parts)
PEAK_TFLOPS = 98.0
PEAK_GBPS = 819.0

#: below this fraction of peak on the BINDING dimension the stage is
#: called dispatch-bound: neither roof explains the measured time
DISPATCH_FRAC = 0.01


def workload_of_record(record: dict):
    """The record's workload (its ``workload`` field, else the bench
    headline defaults) as a meter :class:`Workload` + an ``assumed`` flag.

    No reference counterpart (module docstring)."""
    from disco_tpu.analysis.meter.stages import HEADLINE, Workload

    w = record.get("workload")
    if not isinstance(w, dict):
        return HEADLINE, True
    return Workload(
        batch=int(w.get("batch", HEADLINE.batch)),
        dur_s=float(w.get("dur_s", HEADLINE.dur_s)),
        fs=int(w.get("fs", HEADLINE.fs)),
        n_nodes=int(w.get("n_nodes", HEADLINE.n_nodes)),
        mics_per_node=int(w.get("mics_per_node", HEADLINE.mics_per_node)),
    ), False


def stage_verdicts(record: dict, peak_tflops: float = PEAK_TFLOPS,
                   peak_gbps: float = PEAK_GBPS,
                   dispatch_frac: float = DISPATCH_FRAC) -> dict:
    """The per-stage roofline table of one bench record.

    Returns ``{rows, workload, workload_assumed, peaks,
    cost_model_version}`` where each row carries the measured ``ms``, the
    modeled ``gflops``/``gbytes``, achieved ``gflops_per_s``/``gb_per_s``,
    ``frac_compute``/``frac_bandwidth`` (of the respective peaks) and the
    ``verdict``.  Stages without a measured time (or without a modeled
    cost) are skipped — a roofline never invents a lane.

    No reference counterpart (module docstring).
    """
    from disco_tpu.analysis.meter import costmodel
    from disco_tpu.analysis.meter.stages import STAGE_KEYS, offline_stage_costs

    workload, assumed = workload_of_record(record)
    costs = offline_stage_costs(workload)
    stage_ms = record.get("stage_ms") or {}
    rows = []
    for stage in STAGE_KEYS:
        ms, cost = stage_ms.get(stage), costs.get(stage)
        if not ms or not cost:
            continue
        secs = ms / 1e3
        flops, traffic = cost["flops"], cost["traffic_bytes"]
        achieved_f = flops / secs
        achieved_b = traffic / secs
        frac_c = achieved_f / (peak_tflops * 1e12)
        frac_b = achieved_b / (peak_gbps * 1e9)
        binding = "compute" if frac_c >= frac_b else "bandwidth"
        frac_peak = max(frac_c, frac_b)
        verdict = ("dispatch-bound" if frac_peak < dispatch_frac
                   else f"{binding}-bound")
        rows.append({
            "stage": stage,
            "ms": ms,
            "gflops": round(flops / 1e9, 3),
            "gbytes": round(traffic / 1e9, 3),
            "arithmetic_intensity": cost["arithmetic_intensity"],
            "gflops_per_s": round(achieved_f / 1e9, 2),
            "gb_per_s": round(achieved_b / 1e9, 2),
            "frac_compute": round(frac_c, 6),
            "frac_bandwidth": round(frac_b, 6),
            "fraction_of_peak": round(frac_peak, 6),
            "verdict": verdict,
        })
    return {
        "rows": rows,
        "workload": {
            "batch": workload.batch, "dur_s": workload.dur_s,
            "fs": workload.fs, "n_nodes": workload.n_nodes,
            "mics_per_node": workload.mics_per_node,
        },
        "workload_assumed": assumed,
        "peaks": {"tflops": peak_tflops, "gbps": peak_gbps},
        "cost_model_version": costmodel.VERSION,
    }


def render(result: dict) -> str:
    """The ``disco-obs roofline`` text table.

    No reference counterpart (module docstring)."""
    lines = []
    w = result["workload"]
    src = ("assumed (record predates the workload field)"
           if result["workload_assumed"] else "from record")
    lines.append(
        f"workload: batch={w['batch']} dur_s={w['dur_s']:g} "
        f"K={w['n_nodes']} C={w['mics_per_node']} fs={w['fs']} — {src}")
    p = result["peaks"]
    lines.append(
        f"peaks: {p['tflops']:g} TFLOP/s, {p['gbps']:g} GB/s "
        f"(cost model v{result['cost_model_version']})")
    lines.append(
        f"{'stage':<20}{'ms':>10}{'GFLOP':>10}{'GB':>9}{'AI':>8}"
        f"{'GFLOP/s':>10}{'GB/s':>9}{'%peak':>8}  verdict")
    for r in result["rows"]:
        lines.append(
            f"{r['stage']:<20}{r['ms']:>10.2f}{r['gflops']:>10.2f}"
            f"{r['gbytes']:>9.2f}{r['arithmetic_intensity'] or 0:>8.3f}"
            f"{r['gflops_per_s']:>10.1f}{r['gb_per_s']:>9.1f}"
            f"{r['fraction_of_peak']:>8.2%}  {r['verdict']}"
        )
    if not result["rows"]:
        lines.append("(no stage_ms lanes in this record)")
    return "\n".join(lines)
