"""``make scope-check`` — the causal-tracing / flight-recorder / live-status
gate (the twelfth gate).

Runs the enhancement server in-process on the CPU backend (hermetic:
loopback only, compile cache off, ONE jax process, zero SIGKILLs — the
serve-check discipline) with tracing, the flight recorder and the corpus
tap all armed, and asserts the disco-scope acceptance contract:

1. **Chain completeness**: every delivered frame of every traced client
   reconstructs a COMPLETE causal chain from client seq to tap shard —
   ``client_block → enqueue → dispatch → readback → deliver → tap`` —
   with intact parent links and causal hop order
   (:func:`disco_tpu.obs.trace.verify_chain`), while every session's
   output stays **bit-identical** to the offline ``streaming_tango`` run
   (tracing must observe, never perturb).
2. **Back-compat**: a pre-span client (``trace=False`` — no ``trace``
   header on the wire) is served unchanged (bit-exact) and leaves ZERO
   span events naming its session.
3. **Status/registry agreement**: the read-only ``status`` protocol frame
   answers without a session, and its ``counters`` section equals
   ``obs.REGISTRY.snapshot()["counters"]`` exactly; the SLO evaluator
   renders a verdict over it.
4. **Fault leg**: an injected transport fault (the scheduler's fakeable
   dispatch hook) exhausts the retry budget, quarantines the session, and
   the flight recorder auto-dumps — the dump must **name the failing
   span** (a ``dispatch`` span with ``failed: true`` and the fault's
   error text, same trace as the wounded block) and be **byte-stable**
   (dumping the unchanged ring again yields identical bytes).  The
   wounded session then finishes bit-exact after the injector clears —
   quarantine cost latency, never correctness.

No reference counterpart: the reference has no serving layer and no
telemetry (SURVEY.md §2, §5.1).
"""
from __future__ import annotations

import json
import sys
import tempfile
import threading
from pathlib import Path

K, C, U = 4, 2, 4
BLOCK = 2 * U

#: the serve chain every delivered frame must reconstruct (tap included:
#: the gate runs with the corpus tap armed)
CHAIN = ("client_block", "enqueue", "dispatch", "readback", "deliver", "tap")


def _scene(seed, L=6000):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    return Y, m


def _offline(Y, m, **kw):
    import numpy as np

    from disco_tpu.enhance.streaming import streaming_tango

    return np.asarray(streaming_tango(Y, m, m, update_every=U,
                                      policy="local", **kw)["yf"])


def _config(F, **kw):
    from disco_tpu.serve import SessionConfig

    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                         block_frames=BLOCK, update_every=U, **kw)


def _check_chains_and_status(failures: list, tmp: Path) -> dict:
    """Experiments 1-3: traced clients + one pre-span client through a
    tap-armed loopback server; chain completeness, bit-parity, back-compat
    and status/registry agreement."""
    import numpy as np

    from disco_tpu.flywheel import CorpusTap
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.serve import EnhanceServer, ServeClient
    from disco_tpu.serve.status import evaluate_slo, status_section

    specs = [  # (seed, config kwargs, traced?)
        (71, {}, True),
        (72, {"mu": 1.2}, True),
        (73, {"lambda_cor": 0.97}, True),
        (74, {}, False),   # the pre-span client: no trace header on the wire
    ]
    scenes = [(_scene(seed), ckw, traced) for seed, ckw, traced in specs]
    refs = [_offline(Y, m, **{k: v for k, v in ckw.items()})
            for (Y, m), ckw, _tr in scenes]
    F = scenes[0][0][0].shape[-2]

    tap = CorpusTap(tmp / "tap", records_per_shard=8)
    srv = EnhanceServer(max_sessions=8, tap=tap)
    addr = srv.start()
    results = [None] * len(scenes)
    session_ids = [None] * len(scenes)
    errors: list = []

    def worker(i):
        (Y, m), ckw, traced = scenes[i]
        try:
            cl = ServeClient(addr, trace=True if traced else False)
            session_ids[i] = cl.open(_config(F, **ckw),
                                     session_id=f"scope{i}")
            results[i] = cl.enhance_clip(Y, m, m)
            cl.close()
            cl.shutdown()
        except Exception as e:
            errors.append(f"scope client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(scenes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    failures.extend(errors)

    # quiesce the tap BEFORE the exact counters comparison: its background
    # writer thread bumps tap_shards_written/tap_blocks asynchronously, and
    # a shard landing between the server-side snapshot and the local one
    # would read as disagreement (the gate demands exact equality)
    tap_stats = tap.close()
    if tap_stats["blocks_dropped"]:
        failures.append(
            f"tap dropped {tap_stats['blocks_dropped']} blocks at gate load")

    # experiment 3 while the server is still up and idle: the status frame
    # must agree with the counters registry EXACTLY
    status_client = ServeClient(addr)
    status = status_client.status(timeout_s=30)
    status_client.shutdown()
    counters_now = REGISTRY.snapshot()["counters"]
    if status_section(status, "counters") != counters_now:
        drift = {
            k: (status_section(status, "counters").get(k), counters_now.get(k))
            for k in set(status_section(status, "counters")) | set(counters_now)
            if status_section(status, "counters").get(k) != counters_now.get(k)
        }
        failures.append(f"status counters disagree with the registry: {drift}")
    for name in ("sessions", "scheduler", "latency", "inflight", "gauges"):
        if name not in status:
            failures.append(f"status frame missing the {name!r} section")
    slo = evaluate_slo(status, {"serve_p95_ms": 60000.0,
                                "queue_wait_p95_ms": 60000.0})
    if slo["verdict"] != "OK" or len(slo["checks"]) != 4:
        failures.append(f"SLO evaluator returned {slo} on a healthy idle server")
    srv.stop()

    for i, ref in enumerate(refs):
        if results[i] is None:
            failures.append(f"session {i} returned nothing")
        elif not np.array_equal(results[i], ref):
            failures.append(
                f"session {i} ({'traced' if scenes[i][2] else 'pre-span'}) "
                f"output differs from offline streaming_tango — tracing "
                f"perturbed the pipeline "
                f"(max abs diff {np.abs(results[i] - ref).max():g})"
            )
    return {
        "n_clients": len(scenes),
        "n_blocks": sum(-(-ref.shape[-1] // BLOCK) for ref in refs[:3]),
        "untraced_session": session_ids[3],
        "session_ids": session_ids[:3],
        "tap_shards": tap_stats["shards_written"],
    }


def _verify_chains(failures: list, events: list, info: dict) -> int:
    """Experiment 1's log half: every delivered (session, seq) of every
    traced client has a complete verified chain; experiment 2's half: the
    pre-span session appears in ZERO span events."""
    from disco_tpu.obs import trace as obs_trace

    spans = [e for e in events if e["kind"] == "span"]
    untraced = [e for e in spans
                if e["attrs"].get("session") == info["untraced_session"]]
    if untraced:
        failures.append(
            f"back-compat broken: {len(untraced)} span event(s) name the "
            f"pre-span client's session {info['untraced_session']!r}"
        )
    # deliver spans are the per-frame terminals: group trace ids by
    # (session, seq) and verify each one's full chain
    delivered: dict = {}
    for e in spans:
        if e["stage"] == "deliver":
            key = (e["attrs"].get("session"), e["attrs"].get("seq"))
            delivered[key] = e["attrs"]["trace"]
    expect_per_session = info["n_blocks"] // len(info["session_ids"])
    n_verified = 0
    for sid in info["session_ids"]:
        seqs = sorted(seq for (s, seq) in delivered if s == sid)
        if seqs != list(range(expect_per_session)):
            failures.append(
                f"session {sid}: deliver spans cover seqs {seqs}, expected "
                f"0..{expect_per_session - 1} — not every delivered frame "
                "is traced"
            )
            continue
        for seq in seqs:
            tid = delivered[(sid, seq)]
            try:
                obs_trace.verify_chain(events, tid, require=CHAIN)
                n_verified += 1
            except ValueError as e:
                failures.append(f"chain verification failed: {e}")
    return n_verified


def _check_fault_dump(failures: list, tmp: Path) -> dict:
    """Experiment 4: injected transport fault → quarantine → byte-stable
    flight dump naming the failing span → bit-exact finish."""
    import numpy as np

    from disco_tpu.obs import flight as obs_flight
    from disco_tpu.serve import EnhanceServer, ServeClient
    from disco_tpu.serve.scheduler import set_dispatch_fault_injector

    Y, m = _scene(81)
    F = Y.shape[-2]
    ref = _offline(Y, m)
    dump_dir = tmp / "flight"
    state = {"failures": 0}

    def injector(session_id, seqs):
        if session_id == "wounded" and state["failures"] < 3:
            state["failures"] += 1
            raise OSError("scope-check: injected transport fault")

    # short quarantine so the wounded stream finishes inside the gate
    srv = EnhanceServer(max_sessions=4, quarantine_ticks=3,
                        tick_interval_s=0.001, dispatch_retries=2)
    addr = srv.start()
    set_dispatch_fault_injector(injector)
    try:
        cl = ServeClient(addr, trace=True)
        cl.open(_config(F), session_id="wounded")
        got = cl.enhance_clip(Y, m, m)
        cl.close()
        cl.shutdown()
    finally:
        set_dispatch_fault_injector(None)
        srv.stop()
    if state["failures"] < 3:
        failures.append(
            f"fault injector only fired {state['failures']}/3 times — the "
            "retry budget was never exhausted, nothing was quarantined"
        )
    if not np.array_equal(got, ref):
        failures.append(
            "wounded session's post-quarantine output is not bit-exact "
            f"(max abs diff {np.abs(got - ref).max():g})"
        )
    dumps = sorted(dump_dir.glob("flight-*-quarantine.json"))
    if not dumps:
        failures.append(
            f"no quarantine flight dump under {dump_dir} "
            f"(present: {[p.name for p in dump_dir.glob('*')]})"
        )
        return {"dumps": 0}
    payload = json.loads(dumps[0].read_text())
    entries = [e for ring in payload["subsystems"].values() for e in ring]
    failing = [e for e in entries
               if e["kind"] == "span" and e["attrs"].get("failed")]
    if not failing:
        failures.append(
            "quarantine dump does not name the failing span "
            "(no span entry with failed=true)"
        )
    elif "injected transport fault" not in failing[0]["attrs"].get("error", ""):
        failures.append(
            f"failing span names the wrong error: {failing[0]['attrs']}"
        )
    # byte-stability: the ring is quiet now (server stopped, recorder off
    # for this leg's sinks) — two dumps of the unchanged state must be
    # byte-identical
    a = obs_flight.flight().dump(tmp / "stable_a.json", trigger="manual",
                                 reason="byte-stability probe")
    b = obs_flight.flight().dump(tmp / "stable_b.json", trigger="manual",
                                 reason="byte-stability probe")
    if Path(a).read_bytes() != Path(b).read_bytes():
        failures.append(
            "flight dump is not byte-stable: two dumps of the unchanged "
            "ring differ"
        )
    return {"dumps": len(dumps), "failing_spans": len(failing),
            "injected_failures": state["failures"]}


def main(argv=None) -> int:
    """Run the scope gate (``make scope-check``); exit 1 on any failure."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs
    from disco_tpu.obs import flight as obs_flight
    from disco_tpu.obs import trace as obs_trace

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obs_log = tmp / "scope_check.jsonl"
        obs_trace.enable()
        obs_flight.enable(dump_dir=tmp / "flight")
        try:
            with obs.recording(obs_log):
                obs.write_manifest(tool="scope-check")
                info = _check_chains_and_status(failures, tmp)
                fault = _check_fault_dump(failures, tmp)
                obs.record("counters", **obs.REGISTRY.snapshot())
            events = obs.read_events(obs_log)  # schema-validating read
            n_verified = _verify_chains(failures, events, info)
            if not any(e["kind"] == "flight" for e in events):
                failures.append("event log carries no flight events "
                                "(dump notices missing)")
        finally:
            obs_trace.disable()
            obs_flight.disable()

    if failures:
        for f in failures:
            print(f"scope-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "scope_check": "ok",
        "clients": info["n_clients"],
        "chains_verified": n_verified,
        "tap_shards": info["tap_shards"],
        "flight_dumps": fault["dumps"],
        "injected_failures": fault["injected_failures"],
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
