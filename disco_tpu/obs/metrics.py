"""Counters / gauges / histograms registry + the stage timer.

The Prometheus-style in-process registry production JAX stacks keep next to
their training loops, sized down to zero dependencies: named
:class:`Counter` (monotonic), :class:`Gauge` (last value) and
:class:`Histogram` (count/total/min/max) instruments, a process-global
:data:`REGISTRY` with a ``snapshot()`` dict and pretty-printer, and the
:class:`StageTimer` / :func:`trace_to` profiling tools which moved here from
``disco_tpu.utils.profiling`` (that module keeps a deprecation re-export).

No reference counterpart (SURVEY.md §5.1: the reference's only
instrumentation is ad-hoc ``time.clock()`` prints, train.py:96-103).
"""
from __future__ import annotations

import contextlib
import threading
import time

# NOTE: jax is imported lazily inside StageTimer.stage / trace_to — this
# module sits on the import path of the telemetry reader (cli/obs.py), which
# must stay genuinely jax-free: reading an event log should never pay the
# jax import, let alone touch a device.


class Counter:
    """Monotonic named count (fences, recompiles, clips, sentinel trips).
    Locked: the batched driver increments from scoring worker threads while
    the main thread ticks fences — ``+=`` alone can drop increments."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self.value += n
            return self.value


class Gauge:
    """Last-value instrument (current loss, current RTF)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        self.value = float(v)


class Histogram:
    """Streaming count/total/min/max summary (per-clip durations etc.) —
    enough for a report table without binning policy."""

    __slots__ = ("name", "count", "total", "min", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def summary(self) -> dict:
        return {
            "count": self.count,
            "total": self.total,
            "mean": self.total / self.count if self.count else None,
            "min": self.min,
            "max": self.max,
        }


class Registry:
    """Named instruments, get-or-create.  ``reset()`` zeroes values in place
    so module-level bindings (e.g. the fence counter in ``obs.accounting``)
    stay live across test resets."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name)
            return self._histograms[name]

    def snapshot(self) -> dict:
        """{'counters': {name: int}, 'gauges': {...}, 'histograms': {...}} —
        plain JSON-ready values, the payload of a ``counters`` event."""
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {k: h.summary() for k, h in self._histograms.items()},
            }

    def pretty(self) -> str:
        snap = self.snapshot()
        lines = []
        for name, v in sorted(snap["counters"].items()):
            lines.append(f"counter    {name:28s} {v}")
        for name, v in sorted(snap["gauges"].items()):
            lines.append(f"gauge      {name:28s} {v if v is None else f'{v:g}'}")
        for name, s in sorted(snap["histograms"].items()):
            mean = f"{s['mean']:g}" if s["mean"] is not None else "-"
            lines.append(
                f"histogram  {name:28s} n={s['count']} total={s['total']:g} mean={mean}"
            )
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            for c in self._counters.values():
                c.value = 0
            for g in self._gauges.values():
                g.value = None
            for h in self._histograms.values():
                h.count, h.total, h.min, h.max = 0, 0.0, None, None


#: Process-global registry — the single place run counters accumulate.
REGISTRY = Registry()


class StageTimer:
    """Accumulate named wall-clock stage timings (moved from
    ``utils.profiling``; SURVEY.md §5.1 — replaces the reference's scattered
    ``time.clock()`` prints with one structured object).

    >>> t = StageTimer()
    >>> with t.stage("stft"):
    ...     pass
    >>> "stft" in t.report()
    True
    """

    def __init__(self, sync: bool = True):
        self.sync = sync
        self.times: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def stage(self, name: str, block_on=None):
        start = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None and self.sync:
                import jax

                jax.block_until_ready(block_on)
            dt = time.perf_counter() - start
            self.times[name] = self.times.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> dict:
        """{stage: {'total_s', 'calls', 'mean_s'}} sorted by total time."""
        out = {
            k: {"total_s": v, "calls": self.counts[k], "mean_s": v / self.counts[k]}
            for k, v in self.times.items()
        }
        return dict(sorted(out.items(), key=lambda kv: -kv[1]["total_s"]))

    def pretty(self) -> str:
        lines = [f"{k:24s} {v['total_s']:9.4f}s  x{v['calls']:<5d} {v['mean_s']*1e3:9.3f} ms/call"
                 for k, v in self.report().items()]
        return "\n".join(lines)


@contextlib.contextmanager
def trace_to(logdir: str):
    """Capture a jax.profiler trace into ``logdir`` (view with XProf /
    TensorBoard).  No-op (with a note) if the profiler cannot start —
    tracing must never break the pipeline it observes."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # pragma: no cover - backend-specific
        print(f"[profiling] trace unavailable: {e}")
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()
