"""Counters / gauges / histograms registry + the stage timer.

The Prometheus-style in-process registry production JAX stacks keep next to
their training loops, sized down to zero dependencies: named
:class:`Counter` (monotonic), :class:`Gauge` (last value) and
:class:`Histogram` (count/total/min/max) instruments, a process-global
:data:`REGISTRY` with a ``snapshot()`` dict and pretty-printer, and the
:class:`StageTimer` / :func:`trace_to` profiling tools which moved here from
``disco_tpu.utils.profiling`` (that module keeps a deprecation re-export).

No reference counterpart (SURVEY.md §5.1: the reference's only
instrumentation is ad-hoc ``time.clock()`` prints, train.py:96-103).
"""
from __future__ import annotations

import contextlib
import random
import threading
import time

# NOTE: jax is imported lazily inside StageTimer.stage / trace_to — this
# module sits on the import path of the telemetry reader (cli/obs.py), which
# must stay genuinely jax-free: reading an event log should never pay the
# jax import, let alone touch a device.


class Counter:
    """Monotonic named count (fences, recompiles, clips, sentinel trips).
    Locked: the batched driver increments from scoring worker threads while
    the main thread ticks fences — ``+=`` alone can drop increments."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self.value += n
            return self.value


class Gauge:
    """Last-value instrument (current loss, current RTF)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        self.value = float(v)


#: Retained-sample cap per histogram.  Percentiles (``p50``/``p95``/``p99``
#: in :meth:`Histogram.summary`) come from this bounded reservoir, so a
#: long-lived process (the online enhancement server's request-latency
#: histograms) cannot grow host memory without bound.  Below the cap the
#: percentiles are exact over every observation; past it, classic reservoir
#: sampling keeps a uniform subsample (deterministically seeded — the same
#: observation stream always yields the same report).
RESERVOIR_SIZE = 2048


class Histogram:
    """Streaming count/total/min/max summary plus p50/p95/p99 from a bounded
    sample reservoir (per-clip durations, per-request serve latencies)."""

    __slots__ = ("name", "count", "total", "min", "max", "_samples", "_rng", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: list[float] = []
        self._rng = random.Random(0xD15C0)
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._samples) < RESERVOIR_SIZE:
                self._samples.append(v)
            else:  # reservoir: keep each of the count observations w.p. R/count
                j = self._rng.randrange(self.count)
                if j < RESERVOIR_SIZE:
                    self._samples[j] = v

    @staticmethod
    def _percentile(ordered: list[float], q: float):
        """Linear-interpolated percentile over a sorted sample list (the
        numpy default definition, so tests can pin against np.percentile)."""
        if not ordered:
            return None
        pos = (q / 100.0) * (len(ordered) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(ordered) - 1)
        return ordered[lo] + (ordered[hi] - ordered[lo]) * (pos - lo)

    def percentile(self, q: float):
        """The q-th percentile of the retained samples (exact while count <=
        RESERVOIR_SIZE; a uniform-subsample estimate beyond)."""
        with self._lock:
            ordered = sorted(self._samples)
        return self._percentile(ordered, q)

    def reset(self) -> None:
        """Zero in place (the bench's serve lane resets the latency
        histogram between the compile warm-up and the timed run, so p95
        measures serving, not XLA compiles)."""
        with self._lock:
            self.count, self.total, self.min, self.max = 0, 0.0, None, None
            self._samples.clear()

    def summary(self) -> dict:
        with self._lock:
            ordered = sorted(self._samples)
            count, total = self.count, self.total
            vmin, vmax = self.min, self.max
        return {
            "count": count,
            "total": total,
            "mean": total / count if count else None,
            "min": vmin,
            "max": vmax,
            "p50": self._percentile(ordered, 50.0),
            "p95": self._percentile(ordered, 95.0),
            "p99": self._percentile(ordered, 99.0),
        }


class Registry:
    """Named instruments, get-or-create.  ``reset()`` zeroes values in place
    so module-level bindings (e.g. the fence counter in ``obs.accounting``)
    stay live across test resets."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def peek_counter(self, name: str) -> int:
        """A counter's value WITHOUT creating it (0 when absent).  Readers
        (``accounting.recompile_count``) must not materialize zero-valued
        instruments as a side effect — every counter created here appears
        in ``snapshot()`` and therefore in every later report."""
        with self._lock:
            c = self._counters.get(name)
            return c.value if c is not None else 0

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name)
            return self._histograms[name]

    def snapshot(self) -> dict:
        """{'counters': {name: int}, 'gauges': {...}, 'histograms': {...}} —
        plain JSON-ready values, the payload of a ``counters`` event."""
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {k: h.summary() for k, h in self._histograms.items()},
            }

    def pretty(self) -> str:
        snap = self.snapshot()
        lines = []
        for name, v in sorted(snap["counters"].items()):
            lines.append(f"counter    {name:28s} {v}")
        for name, v in sorted(snap["gauges"].items()):
            lines.append(f"gauge      {name:28s} {v if v is None else f'{v:g}'}")
        for name, s in sorted(snap["histograms"].items()):
            fmt = lambda v: f"{v:g}" if v is not None else "-"
            lines.append(
                f"histogram  {name:28s} n={s['count']} total={fmt(s['total'])} "
                f"mean={fmt(s['mean'])} p50={fmt(s.get('p50'))} "
                f"p95={fmt(s.get('p95'))} p99={fmt(s.get('p99'))}"
            )
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            for c in self._counters.values():
                c.value = 0
            for g in self._gauges.values():
                g.value = None
            for h in self._histograms.values():
                h.reset()


#: Process-global registry — the single place run counters accumulate.
REGISTRY = Registry()


class StageTimer:
    """Accumulate named wall-clock stage timings (moved from
    ``utils.profiling``; SURVEY.md §5.1 — replaces the reference's scattered
    ``time.clock()`` prints with one structured object).

    >>> t = StageTimer()
    >>> with t.stage("stft"):
    ...     pass
    >>> "stft" in t.report()
    True
    """

    def __init__(self, sync: bool = True):
        self.sync = sync
        self.times: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def stage(self, name: str, block_on=None):
        start = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None and self.sync:
                import jax

                jax.block_until_ready(block_on)
            dt = time.perf_counter() - start
            self.times[name] = self.times.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> dict:
        """{stage: {'total_s', 'calls', 'mean_s'}} sorted by total time."""
        out = {
            k: {"total_s": v, "calls": self.counts[k], "mean_s": v / self.counts[k]}
            for k, v in self.times.items()
        }
        return dict(sorted(out.items(), key=lambda kv: -kv[1]["total_s"]))

    def pretty(self) -> str:
        lines = [f"{k:24s} {v['total_s']:9.4f}s  x{v['calls']:<5d} {v['mean_s']*1e3:9.3f} ms/call"
                 for k, v in self.report().items()]
        return "\n".join(lines)


@contextlib.contextmanager
def trace_to(logdir: str):
    """Capture a jax.profiler trace into ``logdir`` (view with XProf /
    TensorBoard).  No-op (with a note) if the profiler cannot start —
    tracing must never break the pipeline it observes."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # pragma: no cover - backend-specific
        print(f"[profiling] trace unavailable: {e}")
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()
