"""Dispatch, fence/RPC and recompile accounting.

On the Axon-tunneled chip every fenced dispatch costs a fixed ~80 ms RPC
round-trip and ``block_until_ready`` returns without waiting (CLAUDE.md), so
the *number of fences* — not wall-clock — is the cost model for host↔device
traffic: ``est rpc ≈ n_fences × 80 ms`` vs. the k-queued-slope on-device
time ``bench.py`` measures.  This module is the counting seam:

* :func:`fence_tick` — called by ``disco_tpu.milestones._fence`` (the one
  reliable execution fence; bench and the validation sweeps all go through
  it) and by the numerics sentinels (each check is one host readback).
* :func:`counted_jit` — a drop-in ``jax.jit`` wrapper for the ``enhance/``
  entry points that detects cache misses by ``_cache_size()`` delta and
  records a ``jit_trace`` event per retrace — the signal that shows, e.g.,
  one compiled program per length bucket in the corpus driver.

Counting stays on even when event recording is off (an int increment per
~80 ms RPC is free); events are only emitted through the no-op-when-disabled
recorder.

No reference counterpart: the reference has no dispatch/fence accounting
of any kind (SURVEY.md §5.1).
"""
from __future__ import annotations

import functools
import threading

from disco_tpu.obs import events as _events
from disco_tpu.obs import metrics as _metrics

#: Measured fixed RPC round-trip per fenced dispatch on the tunneled
#: attachment (BENCH_r03–r05 ``dispatch_overhead_ms``: ~70–80 ms; README
#: "Timing methodology").  An *estimate* for accounting, not a measurement.
RPC_MS_ESTIMATE = 80.0

_FENCES = _metrics.REGISTRY.counter("fences")
_RECOMPILES = _metrics.REGISTRY.counter("jit_recompiles")
_TLS = threading.local()  # per-thread fence count for stage attribution


def fence_tick(n: int = 1) -> None:
    """Count ``n`` execution fences (host readbacks / fenced dispatches)."""
    _FENCES.inc(n)
    _TLS.count = getattr(_TLS, "count", 0) + n


def fence_count() -> int:
    """Process-wide fence count (monotonic)."""
    return _FENCES.value


def fence_count_thread() -> int:
    """Fences ticked by THIS thread.  ``events.stage`` diffs this, not the
    process-wide count: the batched driver scores clips on a thread pool, and
    a global delta would attribute a worker's sentinel readbacks to whatever
    stage the main thread happens to be in."""
    return getattr(_TLS, "count", 0)


def recompile_label(label: str) -> str:
    """Counter name of one label's recompile count
    (``jit_recompiles{label}`` — the Prometheus labeled-series convention,
    flattened into the flat registry namespace)."""
    return f"jit_recompiles{{{label}}}"


def recompile_count(label: str | None = None) -> int:
    """``counted_jit`` recompile count — process-wide, or one label's.

    The per-label series (``jit_recompiles{label}``) is what the trace-
    budget auditor (``disco_tpu.analysis.trace.budgets``) diffs: a budget is
    declared per entry-point label, so the process-wide total — which mixes
    every entry point — cannot arbitrate which label blew its budget.
    ``nn.training.fit`` diffs its own labels for the same reason: an
    unrelated retrace elsewhere in the process must not show up in an epoch
    event as a training-step recompile.
    """
    if label is None:
        return _RECOMPILES.value
    # peek, don't create: a label that never recompiled must not grow a
    # zero-valued counter into every later counters snapshot
    return _metrics.REGISTRY.peek_counter(recompile_label(label))


_DEVICE_GETS = _metrics.REGISTRY.counter("device_get_batches")


def device_get_tick() -> None:
    """Count one BATCHED host readback (``utils.transfer.device_get_tree``):
    a full pytree crossing the boundary in a single ``jax.device_get`` is
    one fenced RPC round on the tunnel, however many leaves it carries —
    the accounting that lets a test assert the corpus engine reads each
    chunk back once instead of K×n_real times (``device_get_batches``)."""
    fence_tick(1)
    _DEVICE_GETS.inc()


def device_get_count() -> int:
    """Process-wide batched-readback count (``device_get_tree`` calls)."""
    return _DEVICE_GETS.value


def rpc_overhead_s(n_fences: int | None = None) -> float:
    """Estimated tunnel-RPC overhead: ``n_fences × ~80 ms``.  Defaults to the
    process-wide fence count."""
    n = fence_count() if n_fences is None else n_fences
    return n * RPC_MS_ESTIMATE / 1e3


def _cache_size(jitted) -> int | None:
    try:
        return jitted._cache_size()
    except Exception:  # pragma: no cover - jax-version dependent API
        return None


def counted_jit(fun=None, *, label: str | None = None, **jit_kwargs):
    """``jax.jit`` with recompile accounting.

    Drop-in for the ``@partial(jax.jit, static_argnames=...)`` entry points
    in ``enhance/``: each call compares the compiled-program cache size
    before/after dispatch; a growth means XLA traced a new program (new
    shapes/dtypes or new static args), which increments the
    ``jit_recompiles`` counter and records a ``jit_trace`` event naming the
    entry point.  The check is two Python attribute reads per call —
    invisible next to any device dispatch.

    Usable bare (``counted_jit(f)``) or with options
    (``@counted_jit(label="run_batch", static_argnames=("k",))``).  The
    underlying jitted callable is exposed as ``.jitted`` (``.lower`` /
    ``.clear_cache`` forward to it).
    """
    if fun is None:
        return functools.partial(counted_jit, label=label, **jit_kwargs)

    import jax

    jitted = jax.jit(fun, **jit_kwargs)
    name = label or getattr(fun, "__name__", "<jit>")

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        before = _cache_size(jitted)
        out = jitted(*args, **kwargs)
        after = _cache_size(jitted)
        if before is not None and after is not None and after > before:
            _RECOMPILES.inc(after - before)
            # per-label series alongside the process-wide total: budgets and
            # the report table are per entry point (see recompile_count)
            _metrics.REGISTRY.counter(recompile_label(name)).inc(after - before)
            _events.record("jit_trace", stage=name, n_new_programs=after - before,
                           cache_size=after)
        return out

    wrapper.jitted = jitted
    wrapper.lower = jitted.lower
    wrapper.clear_cache = getattr(jitted, "clear_cache", None)
    return wrapper
