"""Multi-host (ICI + DCN) mesh construction and distributed runtime init.

The reference's cluster story is rsync staging + single-GPU job arrays
(reference exp/ex1/oar_train.sh:28-45; SURVEY.md §2.7/§5.8).  The TPU-native
equivalent is a JAX multi-process runtime: every host runs the same program,
``jax.distributed`` wires the global device view, and the mesh is laid out so
that the chatty axes (node z-exchange, frame psum) ride ICI within a slice
while only corpus/batch sharding crosses DCN between slices — the
scaling-book recipe.
"""
from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

# Environment signals that this process is part of a multi-process job.
# Checked WITHOUT touching the jax backend: any jax query (process_count,
# devices) would initialise the single-process runtime and make a later
# jax.distributed.initialize impossible.
_ADDRESS_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)
_COUNT_ENV = ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE")
_HOSTLIST_ENV = ("TPU_WORKER_HOSTNAMES",)  # single-host plugins set 'localhost'
_MULTIPROC_ENV = _ADDRESS_ENV + _COUNT_ENV + _HOSTLIST_ENV


def _env_says_multiprocess() -> bool:
    if any(os.environ.get(v) for v in _ADDRESS_ENV):
        return True
    for var in _COUNT_ENV:
        try:
            if int(os.environ.get(var, "1")) > 1:
                return True
        except ValueError:
            pass
    # a hostname LIST (comma-separated) means a real multi-worker pod
    return any("," in os.environ.get(v, "") for v in _HOSTLIST_ENV)


def distributed_init(coordinator_address=None, num_processes=None, process_id=None) -> bool:
    """Initialise the multi-process JAX runtime.

    Must be called BEFORE any other jax API touches the backend.  With no
    arguments it initialises only when the environment indicates a
    multi-process job (TPU pod / SLURM / OpenMPI autodetect); single-process
    runs return False without touching the backend at all.
    """
    explicit = coordinator_address is not None or (num_processes or 0) > 1
    if not explicit and not _env_says_multiprocess():
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError:
        pass  # already initialised
    return True


def hybrid_mesh(n_batch_dcn: int | None = None, n_node: int = 4, n_frame: int = 1, devices=None) -> Mesh:
    """A (batch, node, frame) mesh with 'batch' over DCN (one or more shards
    per host/slice) and 'node'/'frame' over ICI within a slice.

    With ``n_batch_dcn=None`` the batch axis absorbs all remaining devices:
    ``n_devices // (n_node * n_frame)``.  On a true multi-slice TPU this uses
    ``mesh_utils.create_hybrid_device_mesh`` so the axis-to-link assignment is
    physical, not just logical (requires ``n_batch_dcn`` divisible by the
    slice count); single-slice (or CPU test) runs fall back to a plain
    reshape with identical semantics.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    per_batch = n_node * n_frame
    if n_batch_dcn is None:
        n_batch_dcn = max(1, len(devices) // per_batch)
    need = n_batch_dcn * per_batch
    assert len(devices) >= need, (len(devices), n_batch_dcn, n_node, n_frame)
    devices = devices[:need]

    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices > 1:
        assert n_batch_dcn % n_slices == 0, (
            f"batch axis ({n_batch_dcn}) must be divisible by the slice count "
            f"({n_slices}) so DCN only carries the batch dimension"
        )
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(n_batch_dcn // n_slices, n_node, n_frame),
            dcn_mesh_shape=(n_slices, 1, 1),
            devices=devices,
        )
    else:
        arr = np.asarray(devices).reshape(n_batch_dcn, n_node, n_frame)
    return Mesh(arr, axis_names=("batch", "node", "frame"))
