from disco_tpu.parallel.mesh import (
    ring_all_gather,
    make_mesh,
    make_mesh_2d,
    node_sharding,
    shard_map_compat,
    tango_batch_sharded,
    tango_frame_sharded,
    tango_sharded,
)
from disco_tpu.parallel.multihost import distributed_init, hybrid_mesh

__all__ = [
    "ring_all_gather",
    "shard_map_compat",
    "make_mesh",
    "make_mesh_2d",
    "node_sharding",
    "tango_sharded",
    "tango_frame_sharded",
    "tango_batch_sharded",
    "distributed_init",
    "hybrid_mesh",
]
