from disco_tpu.parallel.mesh import make_mesh, node_sharding, tango_sharded

__all__ = ["make_mesh", "node_sharding", "tango_sharded"]
