"""The distributed communication backend: node-parallel TANGO over a device
mesh.

The reference's "distributed" processing is logically distributed but
physically one process — nodes are list indices, and inter-node communication
is ``np.concatenate`` (reference tango.py:142-155; SURVEY.md §0/§2.9).  Here
the node axis is a REAL mesh axis: step 1 runs per-node under ``shard_map``,
and the DANSE z-exchange — each node broadcasting one compressed (F, T)
stream to all others — is exactly one ``jax.lax.all_gather`` over the 'node'
axis, riding ICI on TPU.  This preserves DISCO's bandwidth semantics: one
compressed channel per node crosses the interconnect, never the raw mics.

A 'batch' mesh axis shards rooms/clips (the reference's process-level
``--rirs start n`` data parallelism, SURVEY.md §2.9) — corpus-scale jobs lay
rooms over 'batch' and nodes over 'node' in the same jitted program.

Contract (tested in tests/test_parallel.py): ``tango_sharded`` on an
N-device mesh produces results identical to the single-device ``vmap`` path
``disco_tpu.enhance.tango`` — same math, different placement.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from disco_tpu.enhance.tango import TangoResult, tango_step1, tango_step2


def make_mesh(n_node: int | None = None, n_batch: int = 1, devices=None) -> Mesh:
    """A (batch, node) device mesh.  With ``n_node=None`` all devices not used
    by 'batch' go to 'node'."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_node is None:
        n_node = len(devices) // n_batch
    devices = devices[: n_batch * n_node].reshape(n_batch, n_node)
    return Mesh(devices, axis_names=("batch", "node"))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that lays the leading (node) axis of a (K, ...) array over the
    'node' mesh axis."""
    return NamedSharding(mesh, P("node"))


@partial(
    jax.jit,
    static_argnames=("mesh", "policy", "ref_mic", "mask_type", "oracle_step1_stats"),
)
def tango_sharded(
    Y,
    S,
    N,
    masks_z,
    mask_w,
    mesh: Mesh,
    mu: float = 1.0,
    policy="local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    oracle_step1_stats: bool = False,
) -> TangoResult:
    """Two-step TANGO with the node axis sharded over ``mesh``'s 'node' axis.

    Args:
      Y, S, N: (K, C, F, T) STFT stacks, K == mesh.shape['node'].
      masks_z, mask_w: (K, F, T) step-1/step-2 masks.

    Step 1 is embarrassingly node-parallel; the only cross-device collective
    is the all_gather of the compressed streams (+ masks / oracle refs needed
    by the chosen policy) before step 2 — DANSE's communication pattern.
    """
    K = Y.shape[0]
    assert K % mesh.shape["node"] == 0, (K, dict(mesh.shape))

    shard_map = jax.shard_map

    spec_node = P("node")

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec_node,) * 5,
        out_specs=(spec_node,) * 7,
    )
    def _run(Yk, Sk, Nk, mzk, mwk):
        # Local shard shapes: (1, C, F, T) / (1, F, T) — one node per device.
        step1 = jax.vmap(
            lambda y, s, n, m: tango_step1(
                y, s, n, m, mu=mu, oracle_stats=oracle_step1_stats, ref_mic=ref_mic
            )
        )
        local_z = step1(Yk, Sk, Nk, mzk)

        # THE z-exchange: one compressed stream per node over ICI.
        all_z = {
            key: jax.lax.all_gather(val, "node", axis=0, tiled=True)
            for key, val in local_z.items()
        }
        all_masks_w = jax.lax.all_gather(mwk, "node", axis=0, tiled=True)
        all_S_ref = jax.lax.all_gather(Sk[:, ref_mic], "node", axis=0, tiled=True)
        all_N_ref = jax.lax.all_gather(Nk[:, ref_mic], "node", axis=0, tiled=True)

        k = jax.lax.axis_index("node")
        n_local = Yk.shape[0]  # nodes per device (1 when K == n_devices)
        ks = k * n_local + jnp.arange(n_local)
        step2 = jax.vmap(
            lambda y, s, n, mw, kk: tango_step2(
                y, s, n, mw, kk, all_z, all_masks_w, all_S_ref, all_N_ref,
                mu=mu, policy=policy, ref_mic=ref_mic, mask_type=mask_type,
            ),
            in_axes=(0, 0, 0, 0, 0),
        )
        yf, sf, nf = step2(Yk, Sk, Nk, mwk, ks)
        return yf, sf, nf, local_z["z_y"], local_z["z_s"], local_z["z_n"], local_z["zn"]

    yf, sf, nf, z_y, z_s, z_n, zn = _run(Y, S, N, masks_z, mask_w)
    return TangoResult(
        yf=yf, sf=sf, nf=nf, z_y=z_y, z_s=z_s, z_n=z_n, zn=zn,
        masks_z=masks_z, mask_w=mask_w,
    )
