"""The distributed communication backend: node-parallel (and optionally
frame-parallel) TANGO over a device mesh.

The reference's "distributed" processing is logically distributed but
physically one process — nodes are list indices, and inter-node communication
is ``np.concatenate`` (reference tango.py:142-155; SURVEY.md §0/§2.9).  Here
the node axis is a REAL mesh axis: step 1 runs per-node under ``shard_map``,
and the DANSE z-exchange — each node broadcasting one compressed (F, T)
stream to all others — is exactly one ``jax.lax.all_gather`` over the 'node'
axis, riding ICI on TPU.  This preserves DISCO's bandwidth semantics: one
compressed channel per node crosses the interconnect, never the raw mics.

The STFT frame axis can additionally be sharded over a 'frame' mesh axis —
the framework's sequence parallelism (SURVEY.md §5.7).  Frames are
embarrassingly parallel except for the covariance frame-means, which become
local partial sums + one ``psum`` over 'frame' (see
``disco_tpu.beam.frame_mean_covariance``); filters come out identical on
every frame shard and apply to local frames only.

A 'batch' mesh axis shards rooms/clips (the reference's process-level
``--rirs start n`` data parallelism, SURVEY.md §2.9) — corpus-scale jobs lay
rooms over 'batch' and nodes over 'node' in the same jitted program.

Contract (tested in tests/test_parallel.py): the sharded pipelines on an
N-device mesh produce results identical to the single-device ``vmap`` path
``disco_tpu.enhance.tango`` — same math, different placement.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from disco_tpu.enhance.tango import TangoResult, finite_z_guard, tango_step1, tango_step2
from disco_tpu.ops.cov_ops import resolve_cov_impl


def shard_map_compat(f=None, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the API move: newer jax exposes it as
    ``jax.shard_map(..., check_vma=...)``; before that it lives at
    ``jax.experimental.shard_map.shard_map(..., check_rep=...)`` —
    ``check_rep`` is the same replication/varying-manual-axes check under
    its pre-0.6 name.  The image's jax pinned the older API after round 5
    (MULTICHIP_r05 ran green on the newer one), so every shard_map in this
    repo routes through this seam.  Usable as a decorator factory like
    ``partial(jax.shard_map, ...)``."""
    if f is None:
        return partial(shard_map_compat, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_vma)


def axis_size_compat(axis_name) -> int:
    """Static mesh-axis size inside a shard_map body, across the API move:
    newer jax has ``jax.lax.axis_size``; 0.4.x answers the same question via
    ``jax.core.axis_frame`` (which returns the size directly there, or a
    frame object with ``.size`` on some versions)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return frame.size if hasattr(frame, "size") else frame


def make_mesh(n_node: int | None = None, n_batch: int = 1, devices=None) -> Mesh:
    """A (batch, node) device mesh.  With ``n_node=None`` all devices not used
    by 'batch' go to 'node'."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_node is None:
        n_node = len(devices) // n_batch
    devices = devices[: n_batch * n_node].reshape(n_batch, n_node)
    return Mesh(devices, axis_names=("batch", "node"))


def make_mesh_2d(n_node: int, n_frame: int, devices=None) -> Mesh:
    """A (node, frame) mesh: nodes over one axis, STFT frames (sequence
    parallelism, SURVEY.md §5.7) over the other."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    assert len(devices) >= n_node * n_frame, (len(devices), n_node, n_frame)
    return Mesh(devices[: n_node * n_frame].reshape(n_node, n_frame), axis_names=("node", "frame"))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that lays the leading (node) axis of a (K, ...) array over the
    'node' mesh axis."""
    return NamedSharding(mesh, P("node"))


def ring_all_gather(x, axis_name: str):
    """All-gather over ``axis_name`` built from K-1 ``ppermute`` ring hops —
    the explicit ring-collective formulation (scaling-book style): each
    device forwards what it last received to its ring neighbour, so every
    step moves one shard over one ICI link and compute can overlap
    communication.  Semantically identical to ``jax.lax.all_gather(...,
    tiled=True)`` with the shard's leading axis concatenated in node order.

    Args:
      x: per-device shard, leading axis = local shard rows.
      axis_name: mesh axis to gather over.
    """
    n = axis_size_compat(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]  # send to the next device

    def hop(carry, _):
        received = jax.lax.ppermute(carry, axis_name, perm)
        return received, received

    _, hops = jax.lax.scan(hop, x, None, length=n - 1)  # (n-1, rows, ...)
    # hops[j] on device idx is the shard of device (idx - 1 - j) mod n;
    # scatter all pieces (own + received) into node order.
    pieces = jnp.concatenate([x[None], hops], axis=0)  # (n, rows, ...)
    src_dev = jnp.mod(idx - jnp.arange(n), n)  # piece j came from src_dev[j]
    order = jnp.argsort(src_dev)
    pieces = jnp.take(pieces, order, axis=0)
    return pieces.reshape((-1,) + x.shape[1:])


def _tango_on_mesh(
    Y, S, N, masks_z, mask_w, mesh, frame_axis, mu, policy, ref_mic, mask_type,
    oracle_step1_stats, z_exchange: str = "all_gather", solver: str = "power",
    cov_impl: str = "auto", z_mask=None,
) -> TangoResult:
    """Shared shard_map body for the node-sharded and node+frame-sharded
    pipelines — identical math, different partition specs.

    ``z_exchange``: 'all_gather' (one XLA collective) or 'ring' (explicit
    K-1 ppermute hops, see :func:`ring_all_gather`) — bit-identical results,
    different collective schedules.

    ``z_mask``: optional (K,) per-source availability of the exchanged
    streams.  Each node holds its own flags (sharded over 'node' like the
    z streams themselves) and the mask rides the z-exchange: it is
    all_gathered alongside z, combined with the finiteness guard on the
    gathered streams, and consumed by every node's step 2 — so a node
    whose z was corrupted in flight is excluded consistently everywhere.
    """
    K = Y.shape[0]
    assert K % mesh.shape["node"] == 0, (K, dict(mesh.shape))
    if frame_axis is not None:
        T = Y.shape[-1]
        assert T % mesh.shape[frame_axis] == 0, (T, dict(mesh.shape))

    spec4 = P("node", None, None, frame_axis)
    spec3 = P("node", None, frame_axis)
    spec1 = P("node")

    gather = (
        (lambda v: ring_all_gather(v, "node"))
        if z_exchange == "ring"
        else (lambda v: jax.lax.all_gather(v, "node", axis=0, tiled=True))
    )

    faulty = z_mask is not None
    if faulty:
        z_mask = jnp.asarray(z_mask, Y.real.dtype)
        assert z_mask.shape == (K,), (
            f"sharded tango takes a (K,) = ({K},) per-source z_mask; got {z_mask.shape}"
        )

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(spec4, spec4, spec4, spec3, spec3) + ((spec1,) if faulty else ()),
        out_specs=(spec3,) * 7,
        # pallas_call's vma handling inside shard_map is incomplete in this
        # jax version (its interpreter hits "dynamic_slice requires varying
        # manual axes to match"; upstream suggests check_vma=False as the
        # workaround) — disable the check ONLY when the pallas kernel will
        # actually run: 'auto' resolved first (it may land on pallas on a
        # TPU mesh), and under sequence parallelism (frame_axis set)
        # _masked_cov_pair falls back to the einsum path, which must keep
        # its vma validation.
        check_vma=not (resolve_cov_impl(cov_impl) == "pallas"
                       and frame_axis is None),
    )
    def _run(Yk, Sk, Nk, mzk, mwk, *rest):
        # Local shard shapes: (K_local, C, F, T_local).
        step1 = jax.vmap(
            lambda y, s, n, m: tango_step1(
                y, s, n, m, mu=mu, oracle_stats=oracle_step1_stats, ref_mic=ref_mic,
                frame_axis=frame_axis, solver=solver, cov_impl=cov_impl,
            )
        )
        local_z = step1(Yk, Sk, Nk, mzk)

        # THE z-exchange: one compressed stream per node over ICI (per frame
        # shard when the frame axis is sharded).
        all_z = {key: gather(val) for key, val in local_z.items()}
        all_masks_w = gather(mwk)
        all_S_ref = gather(Sk[:, ref_mic])
        all_N_ref = gather(Nk[:, ref_mic])

        avail = None
        if faulty:
            # The availability flags ride the same collective as the z
            # streams, then the finiteness guard on the GATHERED streams is
            # folded in — corruption is judged on what actually arrived.
            avail = gather(rest[0]) * finite_z_guard(all_z["z_y"])  # (K,)
            if frame_axis is not None:
                # A frame shard only sees its local frames; a stream with
                # non-finite values in SOME shard must be excluded in ALL
                # of them or the per-shard filters diverge.
                avail = jax.lax.pmin(avail, frame_axis)

        k = jax.lax.axis_index("node")
        n_local = Yk.shape[0]  # nodes per device (1 when K == n_devices)
        ks = k * n_local + jnp.arange(n_local)
        step2 = jax.vmap(
            lambda y, s, n, mw, kk: tango_step2(
                y, s, n, mw, kk, all_z, all_masks_w, all_S_ref, all_N_ref,
                mu=mu, policy=policy, ref_mic=ref_mic, mask_type=mask_type,
                frame_axis=frame_axis, solver=solver, cov_impl=cov_impl,
                z_avail=avail,
            ),
            in_axes=(0, 0, 0, 0, 0),
        )
        yf, sf, nf = step2(Yk, Sk, Nk, mwk, ks)
        return yf, sf, nf, local_z["z_y"], local_z["z_s"], local_z["z_n"], local_z["zn"]

    args = (Y, S, N, masks_z, mask_w) + ((z_mask,) if faulty else ())
    yf, sf, nf, z_y, z_s, z_n, zn = _run(*args)
    return TangoResult(
        yf=yf, sf=sf, nf=nf, z_y=z_y, z_s=z_s, z_n=z_n, zn=zn,
        masks_z=masks_z, mask_w=mask_w,
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "policy", "ref_mic", "mask_type", "oracle_step1_stats", "z_exchange", "solver", "cov_impl"),
)
def tango_sharded(
    Y,
    S,
    N,
    masks_z,
    mask_w,
    mesh: Mesh,
    mu: float = 1.0,
    policy="local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    oracle_step1_stats: bool = False,
    z_exchange: str = "all_gather",
    solver: str = "power",
    cov_impl: str = "auto",
    z_mask=None,
) -> TangoResult:
    """Two-step TANGO with the node axis sharded over ``mesh``'s 'node' axis.

    Args:
      Y, S, N: (K, C, F, T) STFT stacks, K divisible by the 'node' size.
      masks_z, mask_w: (K, F, T) step-1/step-2 masks.
      z_mask: optional (K,) per-source availability of the exchanged z
        streams; it rides the z-exchange all_gather and arms the
        finiteness guard (see ``_tango_on_mesh``).  Matches the
        single-device ``tango(z_mask=...)`` results exactly
        (tests/test_fault.py).

    Step 1 is embarrassingly node-parallel; the only cross-device collective
    is the all_gather of the compressed streams (+ masks / oracle refs needed
    by the chosen policy) before step 2 — DANSE's communication pattern.
    """
    return _tango_on_mesh(
        Y, S, N, masks_z, mask_w, mesh, None, mu, policy, ref_mic, mask_type,
        oracle_step1_stats, z_exchange, solver, cov_impl, z_mask,
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "policy", "ref_mic", "mask_type", "oracle_step1_stats", "solver"),
)
def tango_frame_sharded(
    Y,
    S,
    N,
    masks_z,
    mask_w,
    mesh: Mesh,
    mu: float = 1.0,
    policy="local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    oracle_step1_stats: bool = False,
    solver: str = "power",
    z_mask=None,
) -> TangoResult:
    """Two-step TANGO sharded over BOTH the node axis and the STFT frame
    axis — the framework's sequence-parallel mode (SURVEY.md §5.7).

    Args:
      Y, S, N: (K, C, F, T) STFT stacks; K divisible by mesh 'node' size,
        T divisible by mesh 'frame' size.
      masks_z, mask_w: (K, F, T).
      z_mask: optional (K,) per-source z availability (see
        :func:`tango_sharded`); the finiteness guard's verdict is
        pmin-combined across frame shards so a partially-corrupted stream
        is excluded consistently on every shard.

    Contract (tests/test_parallel.py): bit-compatible with the single-device
    ``disco_tpu.enhance.tango`` for every policy.
    """
    return _tango_on_mesh(
        Y, S, N, masks_z, mask_w, mesh, "frame", mu, policy, ref_mic, mask_type,
        oracle_step1_stats, solver=solver, z_mask=z_mask,
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "policy", "ref_mic", "mask_type", "solver", "cov_impl"),
)
def tango_batch_sharded(
    Yb,
    Sb,
    Nb,
    masks_z_b,
    mask_w_b,
    mesh: Mesh,
    mu: float = 1.0,
    policy="local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    solver: str = "power",
    cov_impl: str = "auto",
    z_mask_b=None,
    z_nan_b=None,
) -> TangoResult:
    """Corpus-scale TANGO on a (batch, node) mesh via GSPMD auto-partitioning:
    clips shard over 'batch' (the reference's ``--rirs`` data parallelism as a
    MESH axis instead of a process array), nodes over 'node'.

    Unlike :func:`tango_sharded` (explicit shard_map + all_gather), this is
    the sharding-annotation formulation: the batched single-device program
    ``vmap(tango)`` runs under sharding CONSTRAINTS on its operands and
    outputs, and XLA inserts the node-axis collectives for the z-exchange
    itself — the "pick a mesh, annotate shardings, let the compiler place
    collectives" recipe.  Semantically identical to ``vmap(tango)`` on one
    device (tests/test_parallel.py); compiled once per (mesh, policy, ...)
    combination like the sibling shard_map pipelines.

    Args:
      Yb, Sb, Nb: (B, K, C, F, T) STFT stacks; B divisible by the 'batch'
        mesh size, K by 'node'.
      masks_z_b, mask_w_b: (B, K, F, T).
      z_mask_b: optional per-clip (B, K) or (B, K, K) z availability
        (``tango``'s ``z_mask`` with a leading batch axis).
      z_nan_b: optional (B, K) per-clip NaN-corruption flags
        (``tango``'s ``z_nan``).
    """
    from disco_tpu.enhance.tango import tango

    sh = NamedSharding(mesh, P("batch", "node"))  # trailing dims replicated
    constrain = lambda t: jax.lax.with_sharding_constraint(t, sh)
    Yb, Sb, Nb, masks_z_b, mask_w_b = map(constrain, (Yb, Sb, Nb, masks_z_b, mask_w_b))
    if z_mask_b is None and z_nan_b is None:
        res = jax.vmap(
            lambda Y, S, N, mz, mw: tango(
                Y, S, N, mz, mw, mu=mu, policy=policy, ref_mic=ref_mic,
                mask_type=mask_type, solver=solver, cov_impl=cov_impl,
            )
        )(Yb, Sb, Nb, masks_z_b, mask_w_b)
        return jax.tree_util.tree_map(constrain, res)
    B, K = Yb.shape[:2]
    zmb = jnp.ones((B, K), Yb.real.dtype) if z_mask_b is None else jnp.asarray(z_mask_b)
    znb = jnp.zeros((B, K), bool) if z_nan_b is None else jnp.asarray(z_nan_b)
    res = jax.vmap(
        lambda Y, S, N, mz, mw, zm, zn: tango(
            Y, S, N, mz, mw, mu=mu, policy=policy, ref_mic=ref_mic,
            mask_type=mask_type, solver=solver, cov_impl=cov_impl,
            z_mask=zm, z_nan=zn,
        )
    )(Yb, Sb, Nb, masks_z_b, mask_w_b, zmb, znb)
    return jax.tree_util.tree_map(constrain, res)


def mesh_from_config(cfg) -> Mesh:
    """Build the mesh described by a :class:`disco_tpu.config.MeshConfig`
    (or the root config's ``.mesh``): node-only, node x frame, or the
    hybrid 3-axis layout when a batch axis is requested.

    ``n_node=None`` means "all devices not used by the other axes" on every
    path, so e.g. ``MeshConfig(n_frame=2)`` on 8 devices yields a 4x2 mesh.
    """
    cfg = getattr(cfg, "mesh", cfg)
    n_node = cfg.n_node
    if n_node is None:
        n_node = max(1, len(jax.devices()) // (max(cfg.n_batch, 1) * max(cfg.n_frame, 1)))
    if cfg.n_batch > 1:
        from disco_tpu.parallel.multihost import hybrid_mesh

        return hybrid_mesh(n_batch_dcn=cfg.n_batch, n_node=n_node, n_frame=cfg.n_frame)
    if cfg.n_frame > 1:
        return make_mesh_2d(n_node=n_node, n_frame=cfg.n_frame)
    return make_mesh(n_node=n_node, n_batch=cfg.n_batch)
