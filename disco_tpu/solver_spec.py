"""THE rank-1 GEVD solver-spec grammar — stdlib-only, importable anywhere.

One parser for the ``'base'`` / ``'base:N'`` solver specs shared by the
:func:`disco_tpu.beam.filters.rank1_gevd` dispatch table, the CLI
validator (``cli/common.solver_spec``) and the serve admission check
(``serve.session.SessionConfig``).  It lives OUTSIDE ``beam/filters.py``
because that module imports jax at module level while two of the
grammar's consumers must stay jax-free: the numpy-only serve client
constructs ``SessionConfig`` in its own process (client purity contract,
DL005 — pulling jax into a client host would also re-trigger the
single-chip-claim hazard the contract exists to prevent), and argparse
validation should not pay a jax import to reject a typo.

No reference counterpart: solver selection is a TPU-port concern — the
reference solves every (node, freq) pencil one way only
(``scipy.linalg.eig``, internal_formulas.py:31-81).
"""
from __future__ import annotations

#: every solver spec base the rank-1 GEVD dispatch table accepts
RANK1_SOLVERS = ("eigh", "power", "jacobi", "jacobi-pallas",
                 "fused", "fused-xla", "fused-pallas")

#: the fused solver family's spec -> ``ops.resolve`` impl knob ('fused'
#: resolves per backend exactly like cov_impl/stft_impl 'auto')
FUSED_IMPLS = {"fused": "auto", "fused-xla": "xla", "fused-pallas": "pallas"}


def is_fused_spec(v: str | None) -> bool:
    """True when a solver spec selects the fused rank-1 GEVD-MWF family
    (``'fused'``/``'fused-xla'``/``'fused-pallas'``, optionally ``':N'``).

    THE sanctioned family predicate (DL016 ``fused-solver-selection``):
    call sites that restructure around the fused solve — the step-1 K×F
    pencil batching in ``enhance.tango``, the chained-clip program in
    ``enhance.fused`` — branch through this helper instead of re-spelling
    the family grammar with ``'fused'`` literals or ``startswith`` probes,
    so the branch tracks the grammar when the spec table grows.  ``None``
    (the driver's "defer to the mode default" spelling) is not fused.

    No reference counterpart (module docstring).
    """
    if v is None:
        return False
    return parse_solver_spec(v)[0] in FUSED_IMPLS


def parse_solver_spec(v: str) -> tuple[str, int | None]:
    """THE parser for rank-1 GEVD solver specs — ``'base'`` or ``'base:N'``
    with base in :data:`RANK1_SOLVERS` — shared by ``rank1_gevd``, the CLI
    validator and the serve admission check, so the dispatch table,
    argparse and the wire protocol can never disagree on the grammar.
    Returns (base, N-or-None); raises ValueError on an unknown base, an
    'eigh:N' suffix, or a malformed/empty/<1 N (including multi-colon
    strings).

    No reference counterpart (module docstring).
    """
    base, sep, n_str = v.partition(":")
    if base not in RANK1_SOLVERS:
        raise ValueError(
            f"unknown GEVD solver {v!r}; expected one of {RANK1_SOLVERS}, "
            "optionally with ':N' (power iterations / jacobi sweeps)"
        )
    if not sep:
        return base, None
    if base == "eigh":
        raise ValueError(f"solver spec {v!r}: 'eigh' takes no ':N' suffix")
    try:
        n = int(n_str)
    except ValueError:
        n = 0
    if n < 1:
        raise ValueError(f"malformed solver spec {v!r}: '{base}:N' needs integer N >= 1")
    return base, n
