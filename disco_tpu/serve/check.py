"""``make serve-check`` — the online-serving gate.

Runs the enhancement server in-process on the CPU backend (hermetic: no
network beyond loopback, compile cache off, ONE jax process — the server;
clients are numpy-only threads) and asserts the serve acceptance contract:

1. **Concurrent parity**: ≥4 concurrent streaming clients with different
   clips, smoothing factors and a per-session fault mask — every session's
   output is **bit-identical** to the offline ``streaming_tango`` run of
   the same clip, and the scheduler performed **exactly one batched
   readback per tick-with-work** (``device_get_batches`` accounting, the
   corpus-engine discipline).
2. **Graceful drain**: a SIGINT-equivalent stop (``runs.interrupt``) with a
   half-fed live session — the server stops admitting, finishes every
   queued block, checkpoints the session atomically and closes with its
   resume coordinates; **zero truncated or lost frames** (blocks delivered
   == blocks accepted), and the resumed continuation on a fresh server is
   bit-identical to the uninterrupted offline run.
3. **Chaos**: an injected :class:`~disco_tpu.runs.chaos.ChaosCrash` at the
   ``serve_tick`` seam kills the server mid-stream — every frame a client
   received before the death is complete and bit-correct, nothing is
   half-written; and a ``mid_write`` crash during the drain checkpoint
   leaves **no truncated checkpoint at a final path** (the atomic-write
   invariant), after which a clean drain still checkpoints and resumes.

4. **Tap transparency**: the concurrent-parity experiment runs with the
   flywheel corpus tap enabled (``disco_tpu.flywheel.CorpusTap``) — every
   serve invariant above must hold unchanged (bit-parity, ONE batched
   readback per tick), the tap must spool every delivered block with zero
   drops at this load, every rotated shard must pass its integrity probe,
   and no session may be evicted or backpressured because of the tap.

5. **Overload drill**: sustained flooding past the per-tick block budget
   plus admission attempts past capacity — the server never crashes or
   wedges, over-capacity opens get clean ``capacity`` error frames, the
   degradation ladder steps DOWN deterministically (strictly stepwise ±1
   transitions, ``degraded`` obs events) while queue-wait p95 is hot, no
   parity client is ever shed (``max_rung=2`` for the drill), every
   flooded session still finishes **bit-exact**, and once the load drops
   to zero the ladder recovers to rung 0 (``recovery`` events) within a
   deterministic TICK budget — wait_window_ticks to age the flood out of
   the tick-indexed p95 window plus max_rung·recover_ticks of hysteresis
   walk-down, with slack — after which a fresh session is served
   bit-exact.  Recovery is driven by tick counts, never by wall-clock
   traffic sampling: the old trickle-traffic phase flaked on slow hosts
   whose trickle waits alone kept the window hot.

All crashes are simulated in-process; nothing is ever SIGKILLed
(environment contract).  Wired into ``make test`` alongside ``obs-check``,
``fault-check``, ``chaos-check`` and ``perf-check``.

No reference counterpart: the reference has no serving layer.
"""
from __future__ import annotations

import json
import sys
import tempfile
import threading
from pathlib import Path

K, C, U = 4, 2, 4
BLOCK = 2 * U


def _scene(seed, L=8000):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    return Y, m


def _offline(Y, m, **kw):
    import numpy as np

    from disco_tpu.enhance.streaming import streaming_tango

    return np.asarray(streaming_tango(Y, m, m, update_every=U, policy="local", **kw)["yf"])


def _config(F, **kw):
    from disco_tpu.serve import SessionConfig

    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                         block_frames=BLOCK, update_every=U, **kw)


def _check_parity(failures: list, server_kw: dict | None = None,
                  label: str = "parity") -> dict:
    """Experiment 1: 4 concurrent clients, bit-parity + readback accounting
    (``server_kw``: extra EnhanceServer knobs — the super-tick cycle reruns
    this with ``blocks_per_super_tick=2``)."""
    import numpy as np

    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.serve import EnhanceServer, ServeClient

    specs = [  # (seed, config kwargs, offline kwargs, z_mask)
        (31, {}, {}, None),
        (32, {"mu": 1.2}, {"mu": 1.2}, None),
        (33, {"lambda_cor": 0.97}, {"lambda_cor": 0.97}, None),
        (34, {}, {"z_avail": np.array([1, 0, 1, 1], np.float32)},
         np.array([1, 0, 1, 1], np.float32)),
        # the step-1+step-2 fused solve rides the session config through
        # the same _resolve_step discipline — bit-parity must hold for the
        # fused spec exactly as for eigh (per-block AND super-tick cycles)
        (35, {"solver": "fused-xla"}, {"solver": "fused-xla"}, None),
    ]
    scenes = [(_scene(seed), ckw, okw, zm) for seed, ckw, okw, zm in specs]
    refs = [_offline(Y, m, **okw) for (Y, m), _ckw, okw, _zm in scenes]
    F = scenes[0][0][0].shape[-2]

    srv = EnhanceServer(max_sessions=8, **(server_kw or {}))
    addr = srv.start()
    gets0 = device_get_count()
    results = [None] * len(scenes)
    errors: list = []

    def worker(i):
        (Y, m), ckw, _okw, zm = scenes[i]
        try:
            cl = ServeClient(addr)
            cl.open(_config(F, **ckw), z_mask=zm)
            results[i] = cl.enhance_clip(Y, m, m)
            cl.close()
            cl.shutdown()
        except Exception as e:  # surfaced below, with the session index
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(scenes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    gets = device_get_count() - gets0
    ticks = srv.scheduler.ticks_with_work
    srv.stop()
    failures.extend(errors)
    for i, ref in enumerate(refs):
        if results[i] is None:
            failures.append(f"{label}: session {i} returned nothing")
        elif not np.array_equal(results[i], ref):
            failures.append(
                f"{label}: session {i} output differs from offline streaming_tango "
                f"(max abs diff {np.abs(results[i] - ref).max():g})"
            )
    if gets != ticks:
        failures.append(
            f"{label}: {gets} batched readbacks for {ticks} scheduler ticks — "
            "the one-device_get_tree-per-tick contract is broken"
        )
    return {"sessions": len(scenes), "ticks": ticks, "batched_readbacks": gets,
            "blocks_total": sum(-(-ref.shape[-1] // BLOCK) for ref in refs)}


def _check_drain_resume(failures: list, state_dir: Path,
                        server_kw: dict | None = None) -> dict:
    """Experiment 2: graceful stop drains, checkpoints, resumes bit-exact.
    With super-ticks on, the drain gate must flush the double-buffered
    in-flight batch before checkpointing (block-boundary invariant)."""
    import numpy as np

    from disco_tpu.runs.interrupt import GracefulInterrupt, request_stop
    from disco_tpu.serve import EnhanceServer, ServeClient
    from disco_tpu.serve.session import probe_session_state

    Y, m = _scene(41)
    F, T = Y.shape[-2:]
    ref = _offline(Y, m)
    n_blocks = -(-T // BLOCK)
    half = max(1, n_blocks // 2)

    outs = {}
    with GracefulInterrupt():  # the dispatch loop polls runs.interrupt
        srv = EnhanceServer(max_sessions=4, state_dir=state_dir,
                            **(server_kw or {}))
        addr = srv.start()
        cl = ServeClient(addr)
        cl.open(_config(F), session_id="drainee")
        for i in range(half):
            lo, hi = i * BLOCK, (i + 1) * BLOCK
            cl.send_block(Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
            outs[i] = cl.recv_enhanced(i)
        request_stop("serve-check drain")  # the in-process SIGINT equivalent
        info = cl.wait_closed(timeout_s=120)
        srv.wait(timeout_s=120)
        cl.shutdown()

    if not cl.draining:
        failures.append("drain: client never saw the 'draining' notice")
    if info.get("blocks_done") != half:
        failures.append(
            f"drain: closed at blocks_done={info.get('blocks_done')}, "
            f"expected {half} (lost frames)"
        )
    if len(outs) != half:
        failures.append(f"drain: {len(outs)}/{half} enhanced blocks delivered")
    state_path = info.get("state_path")
    if not state_path or not probe_session_state(state_path):
        failures.append(f"drain: checkpoint missing or fails its probe: {state_path}")

    # resume on a fresh server (the GracefulInterrupt scope is gone, so the
    # stop flag no longer trips the new dispatch loop)
    srv2 = EnhanceServer(max_sessions=4, state_dir=state_dir,
                         **(server_kw or {}))
    addr2 = srv2.start()
    try:
        cl2 = ServeClient(addr2)
        cl2.open(_config(F), resume="drainee")
        if cl2.blocks_done != half:
            failures.append(f"resume: server resumed at {cl2.blocks_done}, expected {half}")
        rest = cl2.enhance_clip(Y, m, m)
        cl2.close()
        cl2.shutdown()
    finally:
        srv2.stop()
    full = np.concatenate(
        [np.concatenate([outs[i] for i in range(half)], axis=-1), rest], axis=-1
    )
    if not np.array_equal(full, ref):
        failures.append(
            f"resume: stitched drain+resume output differs from the offline run "
            f"(max abs diff {np.abs(full - ref).max():g})"
        )
    return {"blocks_before_drain": half, "blocks_total": n_blocks}


def _check_chaos(failures: list, state_dir: Path,
                 server_kw: dict | None = None) -> dict:
    """Experiment 3: chaos crashes — mid-serve and mid-checkpoint."""
    import numpy as np

    from disco_tpu.io.atomic import TMP_SUFFIX
    from disco_tpu.runs import chaos
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError
    from disco_tpu.serve.session import probe_session_state

    Y, m = _scene(51)
    F, T = Y.shape[-2:]
    ref = _offline(Y, m)
    n_blocks = -(-T // BLOCK)
    n_crashes = 0

    # (a) crash the scheduler mid-stream: the 3rd tick dies like a process
    srv = EnhanceServer(max_sessions=4, **(server_kw or {}))
    addr = srv.start()
    cl = ServeClient(addr)
    cl.open(_config(F))
    received: dict = {}
    # arm AFTER block 0 is delivered: the dispatch loop ticks every
    # tick_interval_s even when idle, so arming first would race the
    # client's first send against 3 idle ticks (flaky under CI load)
    cl.send_block(Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK])
    received[0] = cl.recv_enhanced(0, timeout_s=60)
    chaos.configure("serve_tick", after=3)
    try:
        for i in range(1, n_blocks):
            lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
            cl.send_block(Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
            received[i] = cl.recv_enhanced(i, timeout_s=60)
        failures.append("chaos: serve_tick crash never fired")
    except ServeError:
        pass  # the connection died with the server — the expected shape
    finally:
        chaos.disable()
    try:
        srv.wait(timeout_s=60)
        failures.append("chaos: dispatch thread survived the injected crash")
    except chaos.ChaosCrash:
        n_crashes += 1
    cl.shutdown()
    if not received:
        failures.append("chaos: no blocks delivered before the injected crash")
    for i, yf in received.items():
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        if not np.array_equal(yf, ref[..., lo:hi]):
            failures.append(
                f"chaos: block {i} delivered before the crash is not "
                "bit-correct — a truncated/corrupt frame reached a client"
            )

    # (b) crash INSIDE the drain checkpoint write: atomic-write invariant
    srv = EnhanceServer(max_sessions=4, state_dir=state_dir,
                        **(server_kw or {}))
    addr = srv.start()
    cl = ServeClient(addr)
    cl.open(_config(F), session_id="chaotic")
    cl.send_block(Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK])
    cl.recv_enhanced(0, timeout_s=60)
    chaos.configure("mid_write", after=1)
    try:
        srv.stop(timeout_s=120)
        failures.append("chaos: mid_write crash never fired during checkpoint")
    except chaos.ChaosCrash:
        n_crashes += 1
    finally:
        chaos.disable()
    cl.shutdown()
    final = state_dir / "session_chaotic.state.msgpack"
    if final.exists():
        failures.append(
            "chaos: a checkpoint reached its final path through a mid-write "
            "crash (atomic-write invariant broken)"
            if not probe_session_state(final)
            else "chaos: mid_write crash fired after the rename (seam moved?)"
        )
    litter = [str(p) for p in state_dir.rglob(f"*{TMP_SUFFIX}.*")]
    if litter:
        failures.append(f"chaos: checkpoint temp litter not cleaned on unwind: {litter}")
    return {"crashes_injected": n_crashes, "blocks_before_crash": len(received)}


def _check_overload(failures: list) -> dict:
    """Experiment 5: the overload drill (module docstring)."""
    import time

    import numpy as np

    from disco_tpu.serve import (
        DegradationLadder,
        EnhanceServer,
        ServeClient,
        ServeError,
    )

    scenes = [_scene(60 + i, L=16000) for i in range(4)]
    refs = [_offline(Y, m) for (Y, m) in scenes]
    F = scenes[0][0].shape[-2]
    ladder = DegradationLadder(p95_high_ms=4.0, p95_low_ms=2.5,
                               recover_ticks=10, max_rung=2)
    # a deliberately starved tick budget: 4 clients × an 8-block window
    # against 8 blocks/tick keeps real backlog in the queues, so queue-wait
    # p95 goes hot and the ladder must answer
    srv = EnhanceServer(max_sessions=4, max_queue_blocks=8,
                        max_blocks_per_tick=8, blocks_per_super_tick=2,
                        tick_interval_s=0.001, ladder=ladder)
    addr = srv.start()
    results = [None] * len(scenes)
    errors: list = []

    def worker(i):
        Y, m = scenes[i]
        try:
            cl = ServeClient(addr)
            cl.open(_config(F))
            results[i] = cl.enhance_clip(Y, m, m, window=8)
            cl.close()
            cl.shutdown()
        except Exception as e:
            errors.append(f"overload client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(scenes))]
    for t in threads:
        t.start()
    time.sleep(0.3)   # let the flood establish itself
    # sustained admission past capacity: every extra open gets a clean
    # 'capacity' error frame, never a crash or a hang
    rejects = 0
    for _ in range(3):
        extra = ServeClient(addr)
        try:
            extra.open(_config(F))
            extra.close()
        except ServeError as e:
            if e.code == "capacity":
                rejects += 1
        finally:
            extra.shutdown()
    for t in threads:
        t.join(timeout=300)
    failures.extend(errors)
    peak_rung = max((to for (_t, _f, to, _r) in ladder.transitions),
                    default=0)
    if peak_rung < 1:
        failures.append(
            "overload: the ladder never degraded under a flooded tick "
            "budget (queue-wait p95 never went hot?)")
    if rejects < 1:
        failures.append(
            "overload: no admission attempt was rejected past capacity")
    for (tick, frm, to, _r) in ladder.transitions:
        if abs(to - frm) != 1:
            failures.append(
                f"overload: non-stepwise ladder transition {frm}->{to} "
                f"at tick {tick}")
    for i, ref in enumerate(refs):
        if results[i] is None:
            failures.append(f"overload: session {i} returned nothing")
        elif not np.array_equal(results[i], ref):
            failures.append(
                f"overload: session {i} output not bit-exact under the "
                f"degraded ladder (max abs diff "
                f"{np.abs(results[i] - ref).max():g})")

    # phase 2: the load drops to ZERO — the ladder must walk back to rung
    # 0 (recovery events) once the hot wait samples age out of the
    # tick-indexed p95 window.  Recovery is driven by TICK COUNTS, not
    # wall-clock traffic: the scheduler's tick loop keeps running while
    # idle, every tick calls the ladder with the pruned window (an empty
    # window reads p95=0.0 = calm), so rung→0 needs at most
    # wait_window_ticks (aging the flood out) + max_rung·recover_ticks
    # (the hysteresis walk-down) ticks.  The old trickle-traffic loop
    # sampled wall-clock waits and flaked on slow hosts, where the
    # trickle's own waits stayed above p95_low_ms and recovery never
    # fired (the known eleventh-gate host flake) — no traffic means
    # nothing host-speed-dependent feeds the window.
    sched = srv.scheduler
    tick_budget = 4 * (sched.wait_window_ticks
                       + ladder.max_rung * ladder.recover_ticks) + 100
    tick_end = sched.tick_no + tick_budget
    # Hang protection ONLY: the bound that matters is the tick budget
    # (deterministic per host).  A fixed wall-clock deadline here was the
    # last host-speed-dependent term in the drill — on a loaded machine
    # ticks advance slowly but steadily and the old 120 s guard could fire
    # mid-recovery.  The guard now watches tick PROGRESS instead: only a
    # scheduler whose tick counter stops moving entirely for 10 s straight
    # counts as hung, so a slow host just takes longer while a genuinely
    # wedged tick loop still fails fast.
    last_tick = sched.tick_no
    last_progress = time.monotonic()
    while ladder.rung > 0 and sched.tick_no < tick_end:
        now = time.monotonic()
        if sched.tick_no != last_tick:
            last_tick = sched.tick_no
            last_progress = now
        elif now - last_progress > 10.0:
            failures.append(
                "overload: scheduler tick counter stalled for 10 s during "
                f"recovery (stuck at tick {last_tick}, rung {ladder.rung})")
            break
        time.sleep(0.005)
    recovery_ticks_used = tick_budget - max(tick_end - sched.tick_no, 0)
    if ladder.rung != 0:
        failures.append(
            f"overload: ladder stuck at rung {ladder.rung} after "
            f"{recovery_ticks_used} idle ticks (budget {tick_budget}: "
            f"window={sched.wait_window_ticks} + "
            f"{ladder.max_rung}x{ladder.recover_ticks} recover, x4 slack "
            "— no recovery)")
    downs = sum(1 for (_t, frm, to, _r) in ladder.transitions if to < frm)
    if not downs:
        failures.append("overload: no recovery transitions recorded")
    # post-recovery proof: a fresh session through the recovered server
    # still comes out bit-exact (the drill ends where it started)
    Y, m = scenes[0]
    cl = ServeClient(addr)
    cl.open(_config(F))
    after = cl.enhance_clip(Y, m, m, window=8)
    cl.close()
    cl.shutdown()
    srv.stop(timeout_s=120)   # never crashes, never wedges
    if not np.array_equal(after, refs[0]):
        failures.append(
            "overload: post-recovery session not bit-exact (max abs diff "
            f"{np.abs(after - refs[0]).max():g})")
    return {"peak_rung": peak_rung, "capacity_rejects": rejects,
            "transitions": len(ladder.transitions),
            "recoveries": downs,
            "recovery_ticks": recovery_ticks_used,
            "recovery_tick_budget": tick_budget}


def _check_chained(failures: list) -> dict:
    """Experiment 6: the chained (time-domain) lane.  One client streams
    raw float audio windows; the server dispatches each whole window as ONE
    jitted program (window STFT -> masks -> scanned two-step pipeline ->
    ISTFT, :func:`disco_tpu.enhance.fused.streaming_clip_fused`) resolved
    through the same ``_resolve_step`` discipline as every other serve
    step, with the fused batch-in-lanes solver riding the session config —
    so serve output is bit-identical to the offline chained twin by
    construction, continuation state included."""
    import numpy as np

    from disco_tpu.enhance.fused import streaming_clip_fused
    from disco_tpu.serve import EnhanceServer, ServeClient

    F = 257
    block_t = BLOCK
    Lw = (block_t - 1) * (F - 1)
    rng = np.random.default_rng(71)
    wins = [rng.standard_normal((K, C, Lw)).astype(np.float32)
            for _ in range(2)]
    masks = [rng.uniform(0.05, 0.95, size=(K, F, block_t)).astype(np.float32)
             for _ in range(2)]
    refs, state = [], None
    for y, m in zip(wins, masks):
        out = streaming_clip_fused(y, masks_z=m, mask_w=m, update_every=U,
                                   policy="local", state=state,
                                   solver="fused-xla")
        # disco-lint: disable=DL002 -- hermetic CPU gate: two offline reference windows on host arrays, no tunnel crossing to batch
        refs.append(np.asarray(out["yf"]))
        state = out["state"]

    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(_config(F, solver="fused-xla", domain="time"))
        got = []
        for i, (y, m) in enumerate(zip(wins, masks)):
            cl.send_block(y, m, m)
            got.append(cl.recv_enhanced(i, timeout_s=120))
        cl.close()
        cl.shutdown()
    finally:
        srv.stop()
    for i, (g, r) in enumerate(zip(got, refs)):
        if g.shape != r.shape or g.dtype.kind != "f":
            failures.append(
                f"chained: window {i} came back {g.dtype}{g.shape}, "
                f"expected float {r.shape}")
        elif not np.array_equal(g, r):
            failures.append(
                f"chained: window {i} differs from the offline chained twin "
                f"(max abs diff {np.abs(g - r).max():g})")
    return {"windows": len(got)}


def main(argv=None) -> int:
    """Run the online-serving gate (``make serve-check``); exit 1 on failure."""
    import os

    # Hermetic gate: no persistent compile-cache writes from CI (an
    # explicit env value still wins), loopback sockets only, CPU backend
    # (the Makefile forces JAX_PLATFORMS=cpu; a bare run would claim the
    # tunneled chip).
    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obs_log = tmp / "serve_check.jsonl"
        with obs.recording(obs_log):
            obs.write_manifest(tool="serve-check")
            # the base parity cycle runs WITH the corpus tap on: the serve
            # contract must be tap-transparent (experiment 4 above)
            from disco_tpu.flywheel import CorpusTap, list_shards, probe_shard

            tap = CorpusTap(tmp / "tap", records_per_shard=8)
            parity = _check_parity(failures, server_kw={"tap": tap})
            tap_stats = tap.close()
            expected_blocks = parity["blocks_total"]
            if tap_stats["blocks_dropped"]:
                failures.append(
                    f"tap: {tap_stats['blocks_dropped']} blocks dropped at "
                    "parity load — the spool bound is undersized for the gate"
                )
            if tap_stats["blocks_accepted"] != expected_blocks:
                failures.append(
                    f"tap: spooled {tap_stats['blocks_accepted']} blocks, "
                    f"expected {expected_blocks} (one per delivered block)"
                )
            shard_files = list_shards(tmp / "tap")
            if not shard_files:
                failures.append("tap: no shard files written")
            for sp in shard_files:
                if not probe_shard(sp):
                    failures.append(f"tap: shard fails its integrity probe: {sp}")
            drain = _check_drain_resume(failures, tmp / "state")
            chaos_stats = _check_chaos(failures, tmp / "chaos_state")
            # super-tick cycle: the same concurrent-parity, drain/resume and
            # chaos scenarios with blocks_per_super_tick=2 (scanned
            # multi-block dispatch + double-buffered readback) — the serve
            # contract must hold bit-for-bit in super-tick mode too
            st_kw = {"blocks_per_super_tick": 2, "max_queue_blocks": 8}
            st_parity = _check_parity(failures, server_kw=st_kw,
                                      label="supertick-parity")
            _check_drain_resume(failures, tmp / "st_state", server_kw=st_kw)
            st_chaos = _check_chaos(failures, tmp / "st_chaos_state",
                                    server_kw=st_kw)
            chaos_stats["crashes_injected"] += st_chaos["crashes_injected"]
            overload = _check_overload(failures)
            chained = _check_chained(failures)
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(obs_log)  # schema-validating read

        session_events = [e for e in events if e["kind"] == "session"]
        if not any(e["attrs"].get("action") == "open" for e in session_events):
            failures.append("event log missing serve session open events")
        if not any(e["attrs"].get("action") == "drain" for e in session_events):
            failures.append("event log missing the drain session event")
        if not any(e["kind"] == "interrupted" and e["stage"] == "serve" for e in events):
            failures.append("event log missing the serve interrupted event")
        chaos_events = [e for e in events if e["kind"] == "fault"
                        and e["attrs"].get("fault") == "chaos_crash"]
        if len(chaos_events) != chaos_stats["crashes_injected"]:
            failures.append(
                f"event log carries {len(chaos_events)} chaos_crash events, "
                f"expected {chaos_stats['crashes_injected']}"
            )
        ladder_down = [e for e in events if e["kind"] == "degraded"
                       and e["attrs"].get("controller") == "ladder"]
        ladder_up = [e for e in events if e["kind"] == "recovery"
                     and e["attrs"].get("controller") == "ladder"]
        if not ladder_down or not ladder_up:
            failures.append(
                f"event log missing ladder degraded/recovery events "
                f"({len(ladder_down)} down, {len(ladder_up)} up) — "
                "disco-obs report would show no overload story"
            )
        snap = obs.REGISTRY.snapshot()
        lat = snap["histograms"].get("serve_block_latency_ms") or {}
        if not lat.get("count"):
            failures.append("serve_block_latency_ms histogram was never observed")

    if failures:
        for f in failures:
            print(f"serve-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "serve_check": "ok",
        "concurrent_sessions": parity["sessions"],
        "ticks": parity["ticks"],
        "batched_readbacks": parity["batched_readbacks"],
        "supertick_ticks": st_parity["ticks"],
        "supertick_readbacks": st_parity["batched_readbacks"],
        "tap_blocks": tap_stats["blocks_accepted"],
        "tap_shards": tap_stats["shards_written"],
        "drain_blocks": drain["blocks_before_drain"],
        "crashes_injected": chaos_stats["crashes_injected"],
        "overload_peak_rung": overload["peak_rung"],
        "overload_capacity_rejects": overload["capacity_rejects"],
        "overload_recoveries": overload["recoveries"],
        "chained_windows": chained["windows"],
        "jax_processes": 1,   # by construction: clients are numpy threads
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
