"""Per-stream session state of the online enhancement service.

A session is one client's audio stream: open → blocks in → enhanced blocks
out → close.  It wraps exactly the state the streaming pipeline already
defines — the :func:`~disco_tpu.enhance.streaming.streaming_tango`
continuation carry (per-block covariance recursion + last-good-z hold,
DANSE's adaptive block-update design) plus the per-session fault
availability plan — and adds the bookkeeping a scheduler needs: input /
output queues, block accounting, and lifecycle status.

The carry is kept as an **explicit, serializable pytree** from block 0
(:func:`~disco_tpu.enhance.streaming.initial_stream_state`), so a live
session can be checkpointed at any block boundary
(:func:`save_session_state`, atomic msgpack + digest probe) and resumed by
a later server process (:func:`load_session_state`) with bit-identical
continuation — the crash-safety story of ``disco_tpu.runs`` extended to
streams that never had a file to begin with.

No reference counterpart: the reference has no serving layer; session
state is the streaming carry plus admission bookkeeping invented here.
"""
from __future__ import annotations

import dataclasses
import hashlib
import threading
from collections import deque
from pathlib import Path

import msgpack
import numpy as np

#: Session lifecycle states.  ``OPEN``/``DRAINING`` sessions are dispatched
#: by the scheduler tick; ``PARKED`` (connection lost, awaiting reattach
#: within the park TTL) and ``QUARANTINED`` (repeated transport-failed
#: dispatches, cooling off) sessions keep their carry + queue but are
#: skipped by the tick loop; ``CLOSED``/``EVICTED`` are terminal.
OPEN, DRAINING, CLOSED, EVICTED = "open", "draining", "closed", "evicted"
PARKED, QUARANTINED = "parked", "quarantined"

_STATE_VERSION = 1

#: mask-for-z policies the streaming pipeline supports (the oracle policies
#: are offline-only — enhance/streaming._stream_stats).
SERVE_POLICIES = ("local", "distant", "none")

#: Where a session's masks come from.  ``"client"`` (default, the PR-16
#: wire shape): every block frame carries ``mask_z``/``mask_w``.
#: ``"model"``: blocks arrive maskless and the scheduler fills both masks
#: at dispatch time from the session's current weight generation
#: (:mod:`disco_tpu.promote.lane`) — requires the server to run with a
#: promote store (``--promote-dir``).
MASK_SOURCES = ("client", "model")

#: Wire domain of a session's blocks.  ``"stft"`` (default, the PR-16 wire
#: shape): blocks are (K, C, F, T) complex STFT frames, outputs (K, F, T)
#: complex — the client owns the transforms.  ``"time"`` (the chained
#: lane): each block is one (K, C, samples) float super-tick *window*,
#: dispatched whole through the one-program chained twin
#: (:func:`disco_tpu.enhance.fused.streaming_clip_fused` — window STFT,
#: masks, scanned two-step pipeline and ISTFT all inside one jitted
#: program), and the delivered output is the (K, samples) enhanced float
#: window.  Masks still ride the wire in the STFT grid (K, F, T_frames).
DOMAINS = ("stft", "time")


@dataclasses.dataclass(frozen=True)
class SessionConfig:
    """Static per-session configuration — the shape-bucket key.

    Two sessions with equal configs share one compiled program (the
    ``streaming_tango`` jit cache keys on shapes + static args), which is
    what bounds serve-side recompiles; ``block_frames`` is therefore fixed
    per session and every block but the last must carry exactly that many
    STFT frames (a shorter final block compiles one extra ragged program).
    """

    n_nodes: int
    mics_per_node: int
    n_freq: int
    block_frames: int
    update_every: int = 4
    lambda_cor: float = 0.99
    mu: float = 1.0
    ref_mic: int = 0
    policy: str = "local"
    solver: str = "eigh"
    masks: str = "client"
    domain: str = "stft"

    def __post_init__(self):
        # lambda_cor / mu are traced floats with an omit-when-default calling
        # convention (streaming._float_kw): coerce wire-decoded values here so
        # a msgpack/JSON integer mu=1 still reads as the 1.0 default (omitted,
        # shared program) instead of tracing a third int-typed program per
        # shape bucket.
        for f in ("lambda_cor", "mu"):
            v = getattr(self, f)
            if not isinstance(v, float):
                try:
                    object.__setattr__(self, f, float(v))
                except (TypeError, ValueError):
                    raise ValueError(
                        f"session config {f!r}: expected a float, got {v!r}"
                    ) from None
        for f in ("n_nodes", "mics_per_node", "n_freq", "block_frames", "update_every"):
            v = getattr(self, f)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"session config {f!r}: expected a positive int, got {v!r}")
        if self.n_nodes < 2:
            raise ValueError(
                f"session config n_nodes: the distributed exchange needs >= 2 "
                f"nodes, got {self.n_nodes}"
            )
        if self.block_frames % self.update_every:
            raise ValueError(
                f"session config block_frames ({self.block_frames}) must be a "
                f"multiple of update_every ({self.update_every}): chunk-exact "
                f"streaming continuation needs refresh-aligned block boundaries"
            )
        if not 0 <= self.ref_mic < self.mics_per_node:
            raise ValueError(
                f"session config ref_mic {self.ref_mic} outside [0, "
                f"{self.mics_per_node})"
            )
        if self.policy not in SERVE_POLICIES:
            raise ValueError(
                f"session config policy {self.policy!r} not servable; one of "
                f"{SERVE_POLICIES} (oracle policies are offline-only)"
            )
        if self.masks not in MASK_SOURCES:
            raise ValueError(
                f"session config masks {self.masks!r} unknown; one of "
                f"{MASK_SOURCES}"
            )
        if self.domain not in DOMAINS:
            raise ValueError(
                f"session config domain {self.domain!r} unknown; one of "
                f"{DOMAINS}"
            )
        if self.domain == "time":
            # the chained lane's window STFT derives its hop from the
            # config's frequency grid (hop = n_fft/2 = n_freq - 1); the
            # model-mask lane estimates masks from STFT-domain wire blocks
            # (promote/lane.block_masks) which a time session never sends
            if self.n_freq < 2:
                raise ValueError(
                    "session config domain='time' needs n_freq >= 2 "
                    "(hop is derived as n_freq - 1)"
                )
            if self.masks != "client":
                raise ValueError(
                    "session config domain='time' supports masks='client' "
                    "only: the model-mask lane fills masks from STFT wire "
                    "blocks, which a time-domain session never sends"
                )
        if not 0.0 < float(self.lambda_cor) < 1.0:
            raise ValueError(
                f"session config lambda_cor must be in (0, 1), got {self.lambda_cor!r}"
            )
        # THE shared solver grammar (disco_tpu.solver_spec — the same
        # validator the CLI and the rank1_gevd dispatch use), so a bad
        # wire-decoded spec fails at admission with a clean error instead
        # of at first dispatch inside the tick loop.  solver_spec is
        # stdlib-only: SessionConfig is constructed in the numpy-only
        # CLIENT process too, which must never import jax (DL005 purity /
        # single-chip-claim contract).
        from disco_tpu.solver_spec import parse_solver_spec

        try:
            parse_solver_spec(self.solver)
        except ValueError as e:
            raise ValueError(f"session config solver: {e}") from None

    @property
    def hop(self):
        """STFT hop of the chained (time-domain) lane's window transform —
        derived from the config's frequency grid (n_fft/2 = n_freq - 1)."""
        return self.n_freq - 1

    @property
    def block_samples(self):
        """Samples per full time-domain window: the window whose STFT has
        exactly ``block_frames`` frames (T = 1 + samples // hop)."""
        return (self.block_frames - 1) * self.hop

    def frames_of(self, samples: int) -> int:
        """STFT frame count of a ``samples``-long time window."""
        return 1 + samples // self.hop

    @property
    def block_shape(self):
        """Shape of one input block: (K, C, F, T) mixture STFT frames for
        ``domain='stft'``, (K, C, samples) float window for ``'time'``."""
        if self.domain == "time":
            return (self.n_nodes, self.mics_per_node, self.block_samples)
        return (self.n_nodes, self.mics_per_node, self.n_freq, self.block_frames)

    @property
    def mask_shape(self):
        return (self.n_nodes, self.n_freq, self.block_frames)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SessionConfig":
        if not isinstance(d, dict):
            raise ValueError(f"session config: expected a mapping, got {type(d).__name__}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"session config: unknown field(s) {unknown}")
        return cls(**d)


class Session:
    """One live stream: config + streaming carry + queues + accounting.

    The scheduler owns ``state`` (a device pytree between ticks) and the
    input queue; the server's connection handler owns the output delivery.
    All queue operations are lock-protected — blocks arrive on the asyncio
    I/O thread while the dispatch thread drains them.
    """

    def __init__(self, session_id: str, config: SessionConfig, *,
                 z_avail=None, state=None, blocks_done: int = 0,
                 priority: bool = False, replay_blocks: int = 64):
        self.id = session_id
        self.config = config
        #: (K,) or (K, B_plan) float availability of the exchanged streams —
        #: the per-session fault plan (``disco_tpu.fault``); None = fault-free.
        self.z_avail = None if z_avail is None else np.asarray(z_avail, np.float32)
        #: streaming_tango continuation carry (device pytree between ticks;
        #: host pytree right after open/resume).
        self.state = state
        self.status = OPEN
        self.blocks_done = int(blocks_done)   # blocks fully enhanced + delivered to the writer
        self.blocks_in = int(blocks_done)     # highest contiguous seq accepted + 1
        #: blocks dispatched on device but not yet read back (the scheduler's
        #: double-buffered super-tick overlap) — dispatch-thread-only, so no
        #: lock; a session only finishes once queue AND inflight are empty
        self.inflight = 0
        self.close_requested = False
        self._lock = threading.Lock()
        self._pending: list = []              # [(seq, Y, mask_z, mask_w)] FIFO
        self.error: str | None = None
        #: wall-clock enqueue time per pending seq (latency accounting)
        self.enqueued_at: dict[int, float] = {}
        #: causal-trace context per in-flight seq (obs.trace SpanCtx) —
        #: populated only for traced blocks while tracing is enabled;
        #: empty (and untouched) for pre-span clients, so back-compat is
        #: structural.  Guarded by the queue lock: the I/O thread stores at
        #: enqueue while the dispatch thread advances per hop.
        self.trace_ctx: dict[int, object] = {}
        #: newest delivered (seq, yf) host blocks, bounded — the reattach
        #: replay buffer: outputs delivered while the connection was down
        #: are re-sent from here so a parked-and-reattached stream stitches
        #: bit-exact with zero lost frames (scheduler-side, so it survives
        #: the connection that died)
        self.replay: "deque[tuple[int, np.ndarray]]" = deque(maxlen=max(1, replay_blocks))
        #: ladder shedding spares priority sessions (wire ``open`` field)
        self.priority = bool(priority)
        #: admission sequence number (shedding targets the NEWEST
        #: non-priority session — earlier streams keep their progress)
        self.open_seq = 0
        #: monotonic park timestamp while PARKED (TTL accounting), else None
        self.parked_at: float | None = None
        #: lifetime count of transport-exhausted quarantines — the
        #: scheduler's ``max_quarantines``-th offense evicts
        self.quarantine_count = 0
        #: scheduler tick number at which a QUARANTINED session re-opens
        self.quarantine_until_tick = 0
        #: current weight generation id for model-mask sessions (None for
        #: client-mask sessions and promote-less servers).  Written ONLY by
        #: the dispatch thread at block boundaries (inflight == 0, between
        #: dispatches — ``Scheduler._apply_generation_swaps``), so every
        #: block is computed under exactly one generation.
        self.generation: str | None = None
        #: [(first_seq, gen_id)] — the session's generation history, one
        #: entry per adoption/swap, first_seq ascending.  What makes a
        #: delivered frame's generation derivable (:meth:`gen_for`) and the
        #: per-generation bit-exact replay of ``make promote-check``
        #: checkable.  Dispatch-thread-only, like :attr:`generation`.
        self.gen_segments: list = []
        #: tick of this session's last outage transition (park, reattach,
        #: quarantine, release).  Queue-wait samples observed within the
        #: scheduler's grace window after it are EXCLUDED from the
        #: degradation ladder's p95: a block that waited out a park or a
        #: retry storm measures the outage, not the load, and feeding it to
        #: the ladder would shed the very session that just survived
        #: (outage → hot p95 → shed → park → outage: a feedback loop).
        #: The serve_queue_wait_ms histogram still sees every sample —
        #: latency accounting stays honest, only the controller is gated.
        self.outage_tick = -(1 << 30)

    # -- input side (I/O thread) --------------------------------------------
    def push_block(self, seq: int, Y, mask_z, mask_w, t_wall: float,
                   trace_ctx=None) -> None:
        with self._lock:
            self._pending.append((int(seq), Y, mask_z, mask_w))
            self.enqueued_at[int(seq)] = t_wall
            self.blocks_in = max(self.blocks_in, int(seq) + 1)
            if trace_ctx is not None:
                self.trace_ctx[int(seq)] = trace_ctx

    def set_trace(self, seq: int, ctx) -> None:
        """Advance one in-flight block's causal-trace head (dispatch
        thread; see :attr:`trace_ctx`).

        No reference counterpart (module docstring)."""
        with self._lock:
            self.trace_ctx[int(seq)] = ctx

    def get_trace(self, seq: int):
        """The block's current trace context, or None (untraced).

        No reference counterpart (module docstring)."""
        with self._lock:
            return self.trace_ctx.get(int(seq))

    def pop_trace(self, seq: int):
        """Take (and drop) the block's trace context at delivery.

        No reference counterpart (module docstring)."""
        with self._lock:
            return self.trace_ctx.pop(int(seq), None)

    def drain_traces(self) -> list:
        """Clear every stored trace context, returning the seqs — the
        terminal-state cleanup (evict/close/park-expiry) that keeps the
        tracer's in-flight table from accumulating ghost entries for
        blocks that will never deliver.

        No reference counterpart (module docstring)."""
        with self._lock:
            seqs = list(self.trace_ctx)
            self.trace_ctx.clear()
        return seqs

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- dispatch side (scheduler thread) -----------------------------------
    def pop_blocks(self, max_n: int) -> list:
        """Take up to ``max_n`` queued blocks, in arrival order."""
        with self._lock:
            take, self._pending = self._pending[:max_n], self._pending[max_n:]
            return take

    def requeue_front(self, blocks: list) -> None:
        """Return popped-but-undispatched blocks to the FRONT of the queue,
        order preserved — a transport-exhausted dispatch must not lose or
        reorder the stream (the carry never advanced for these blocks, so a
        later retry is bit-identical).  Enqueue times stay in
        ``enqueued_at``: the eventual latency observation charges the whole
        outage, honestly.

        No reference counterpart (module docstring)."""
        if not blocks:
            return
        with self._lock:
            self._pending = list(blocks) + self._pending

    def record_delivery(self, seq: int, yf) -> None:
        """Remember one delivered output block in the bounded replay buffer
        — the source of truth the server's posting cursor drains, and what
        a reattaching client's missed frames are re-sent from (see
        :attr:`replay`).  Locked: the dispatch thread appends while the I/O
        thread may be validating a reattach.

        No reference counterpart (module docstring)."""
        with self._lock:
            self.replay.append((int(seq), yf))

    def replay_from(self, have: int) -> list:
        """Buffered deliveries with ``seq >= have``, in order — the frames
        a client's posting cursor at ``have`` has not seen.  Raises
        :class:`SessionStateError` when the buffer no longer reaches back
        to ``have`` (delivered frames would be lost; the reattach must be
        refused, not stitched with a hole).  Locked against concurrent
        :meth:`record_delivery`; the consistency check uses the buffer's
        own newest seq, so a ``blocks_done`` racing ahead can never fail a
        valid reattach.

        No reference counterpart (module docstring)."""
        with self._lock:
            entries = list(self.replay)
        missing = [(s, yf) for (s, yf) in entries if s >= have]
        newest = entries[-1][0] if entries else self.blocks_done - 1
        expect = list(range(have, newest + 1))
        if [s for (s, _) in missing] != expect:
            raise SessionStateError(
                f"session {self.id}: replay buffer no longer covers blocks "
                f"[{have}, {newest + 1}) — the client was gone longer "
                f"than replay_blocks deliveries; cannot reattach without "
                f"losing frames"
            )
        return missing

    def set_generation(self, gen_id: str, at_seq: int) -> None:
        """Adopt a weight generation from block ``at_seq`` on (dispatch
        thread, at a block boundary only — see :attr:`generation`).  A
        re-adoption of the current generation is a no-op segment-wise.

        No reference counterpart (module docstring)."""
        if self.generation == gen_id:
            return
        self.generation = gen_id
        self.gen_segments.append((int(at_seq), gen_id))

    def gen_for(self, seq: int) -> str | None:
        """Generation that computed block ``seq`` (latest segment whose
        ``first_seq`` <= seq), or None for an ungenerationed session.

        No reference counterpart (module docstring)."""
        gen = None
        for first_seq, gen_id in self.gen_segments:
            if first_seq <= int(seq):
                gen = gen_id
        return gen

    def block_z_avail(self, seq: int, n_blocks: int):
        """Availability columns for input block ``seq`` (``n_blocks``
        refresh blocks wide): slice of the per-session plan, all-ones when
        fault-free or past the plan horizon (plan columns are per
        ``update_every`` refresh block)."""
        K = self.config.n_nodes
        if self.z_avail is None:
            return np.ones((K, n_blocks), np.float32)
        if self.z_avail.ndim == 1:
            return np.broadcast_to(self.z_avail[:, None], (K, n_blocks)).copy()
        per_block = self.config.block_frames // self.config.update_every
        b0 = seq * per_block
        cols = np.ones((K, n_blocks), np.float32)
        hi = min(self.z_avail.shape[1], b0 + n_blocks)
        if b0 < hi:
            cols[:, : hi - b0] = self.z_avail[:, b0:hi]
        return cols


# -- checkpointing -----------------------------------------------------------
def _pack_tree(tree):
    """Nested dict/tuple/list pytree of numpy arrays -> msgpack-ready
    structure (arrays via the wire codec — complex-safe, self-describing)."""
    from disco_tpu.serve.protocol import encode_array

    if isinstance(tree, dict):
        return {"__map__": {k: _pack_tree(v) for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        return {"__seq__": [_pack_tree(v) for v in tree]}
    return encode_array(np.asarray(tree))


def _unpack_tree(obj):
    from disco_tpu.serve.protocol import decode_array

    if isinstance(obj, dict) and "__map__" in obj:
        return {k: _unpack_tree(v) for k, v in obj["__map__"].items()}
    if isinstance(obj, dict) and "__seq__" in obj:
        return tuple(_unpack_tree(v) for v in obj["__seq__"])
    return decode_array(obj)


class SessionStateError(ValueError):
    """A session checkpoint failed its integrity probe or config check."""


def save_session_state(path, session: Session, state_host=None) -> Path:
    """Checkpoint one live session's continuation carry atomically.

    The carry (``session.state``) is fetched to host complex-safely in one
    batched readback if it still lives on device, packed as msgpack with a
    sha256 digest of the state payload embedded, and placed with the
    tmp+fsync+``os.replace`` protocol of :mod:`disco_tpu.io.atomic` — an
    interrupted server can never leave a truncated checkpoint at the final
    path (the ``mid_write`` chaos seam fires inside, so the serve chaos
    cycle proves it).

    ``state_host``: pass an already-fetched host pytree to skip the device
    readback (the drain path fetches all sessions' states in one
    ``device_get_tree``).
    """
    from disco_tpu.io.atomic import atomic_write

    if state_host is None:
        state_host = fetch_state_host(session.state)
    state_bytes = msgpack.packb(_pack_tree(state_host), use_bin_type=True)
    payload = msgpack.packb(
        {
            "version": _STATE_VERSION,
            "session": session.id,
            "config": session.config.to_dict(),
            "blocks_done": session.blocks_done,
            "z_avail": None if session.z_avail is None
            else _pack_tree(session.z_avail),
            "state": state_bytes,
            "state_sha256": hashlib.sha256(state_bytes).hexdigest(),
        },
        use_bin_type=True,
    )
    path = Path(path)
    with atomic_write(path) as fh:
        fh.write(payload)
    return path


def probe_session_state(path) -> bool:
    """True iff ``path`` holds a complete, digest-consistent checkpoint —
    the validate-before-trust probe of the resume path (a checkpoint
    truncated behind the atomic writer's back must read as not-done)."""
    try:
        load_session_state(path)
        return True
    except Exception:
        return False


def load_session_state(path) -> Session:
    """Load a checkpoint into a fresh :class:`Session` (host-side state;
    the scheduler devices it on the first tick).  Raises
    :class:`SessionStateError` on truncation, digest mismatch, or a config
    that no longer validates."""
    try:
        raw = Path(path).read_bytes()
        d = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    except Exception as e:
        raise SessionStateError(f"{path}: not a readable session checkpoint: {e}") from None
    if not isinstance(d, dict) or d.get("version") != _STATE_VERSION:
        raise SessionStateError(
            f"{path}: unknown checkpoint version {d.get('version') if isinstance(d, dict) else d!r}"
        )
    state_bytes = d.get("state")
    digest = d.get("state_sha256")
    if not isinstance(state_bytes, bytes) or not digest:
        raise SessionStateError(f"{path}: checkpoint missing state payload/digest")
    if hashlib.sha256(state_bytes).hexdigest() != digest:
        raise SessionStateError(
            f"{path}: state digest mismatch — checkpoint corrupt, refusing to resume"
        )
    try:
        state = _unpack_tree(msgpack.unpackb(state_bytes, raw=False, strict_map_key=False))
        config = SessionConfig.from_dict(d["config"])
    except (KeyError, ValueError) as e:
        raise SessionStateError(f"{path}: bad checkpoint contents: {e}") from None
    z_avail = d.get("z_avail")
    return Session(
        str(d.get("session")), config,
        z_avail=None if z_avail is None else _unpack_tree(z_avail),
        state=state, blocks_done=int(d.get("blocks_done", 0)),
    )


def fetch_state_host(state):
    """Device carry -> host numpy pytree in ONE complex-safe batched
    readback (:func:`disco_tpu.utils.transfer.device_get_tree`); host
    pytrees pass through untouched (no jax import needed)."""
    leaves_on_host = all(
        isinstance(x, np.ndarray)
        for x in _iter_leaves(state)
    )
    if leaves_on_host:
        return state
    from disco_tpu.utils.transfer import device_get_tree

    return device_get_tree(state)


def _iter_leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _iter_leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _iter_leaves(v)
    else:
        yield tree
