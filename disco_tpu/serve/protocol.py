"""Wire protocol of the online enhancement service: length-prefixed msgpack
frames over a stream socket.

Every frame is a 4-byte big-endian payload length followed by one msgpack
map with a ``"type"`` key.  Arrays travel as self-describing maps
(``{"__nd__": 1, "dtype", "shape", "data"}``; complex dtypes are split into
``data``/``imag`` float halves — the same real-pair convention as
``disco_tpu.utils.transfer``, though here it is a portability choice, not a
tunnel workaround: msgpack has no complex type).  Everything in this module
is **numpy + stdlib only** — a serve client must never import jax (the
environment contract allows ONE chip-claiming process, and that is the
server; ``tests/test_serve.py`` pins the import graph).

Frame types (client → server):

* ``open``    — start (or resume/reattach) a session; carries the
  :class:`~disco_tpu.serve.session.SessionConfig` fields and optionally:
  ``z_mask``; ``resume`` (the resume token — a parked session reattaches
  in place, otherwise the server falls back to its checkpoint); ``have``
  (the next output seq the client still needs — the server replays the
  parked session's missed deliveries from its bounded replay buffer, so
  nothing is lost or duplicated); ``priority`` (ladder shedding spares
  priority sessions).
* ``block``   — one streaming input block: ``seq`` (0-based block index),
  ``Y`` (K, C, F, T) complex64 mixture STFT frames, ``mask_z`` / ``mask_w``
  (K, F, T) step-1/2 masks; optionally ``trace`` — the causal-tracing
  header (``{"trace": <id>, "span": <id>}``, ``disco_tpu.obs.trace``)
  minted at submission so the server can thread the block's span chain.
  **Back-compat**: the header is optional and unvalidated-by-rejection — a
  pre-span client (no ``trace`` key) is served byte-for-byte unchanged.
* ``close``   — no more blocks; flush and finish the session.
* ``status``  — read-only live introspection: no session required, never
  mutates anything; the server answers with one ``status_ok`` frame.

Server → client:

* ``open_ok``  — session admitted: ``session`` id, ``blocks_done`` (>0 when
  resumed from a checkpoint), ``next_seq`` (the next INPUT seq the server
  expects — after a reattach the client re-sends from here, the same
  rollback that serves backpressure), ``reattached`` (true when a parked
  session was stitched in place).
* ``enhanced`` — one enhanced output block: ``seq``, ``yf`` (K, F, T)
  complex64 — the streaming TANGO outputs for the matching input block.
* ``draining`` — the server received a graceful stop: the session's queued
  blocks will still be enhanced and delivered, then the session is
  checkpointed and closed; stop sending new blocks.
* ``closed``   — session over: ``blocks_done``, optional ``state_path`` of
  the checkpoint a resumed session can continue from.
* ``status_ok`` — the ``status`` reply: the
  :func:`~disco_tpu.serve.status.status_payload` sections (session states,
  scheduler tick, ladder rung, counters/gauges, latency percentiles,
  in-flight spans) — the ``disco-obs top`` / ``disco-obs slo`` surface.
* ``error``    — admission rejection, eviction, protocol violation;
  ``code`` + human-readable ``message``.  Code ``parked`` is special: the
  session was parked (connection trouble or ladder shedding), and the
  frame carries ``resume`` (the token to reattach with) and
  ``retry_after_s`` (a back-off hint for shed sessions) —
  :class:`~disco_tpu.serve.client.ServeClient` reattaches transparently.

No reference counterpart: the reference pipeline is strictly offline
(SURVEY.md §2) — this protocol is the seam that turns it into a service.
"""
from __future__ import annotations

import socket
import struct

import msgpack
import numpy as np

#: Hard per-frame size bound (64 MiB).  A corrupt / hostile length prefix
#: must fail fast instead of allocating unbounded memory server-side.
MAX_FRAME_BYTES = 64 << 20

_LEN = struct.Struct(">I")


class ProtocolError(RuntimeError):
    """A malformed frame (bad length prefix, bad msgpack, bad array map)."""


# -- array codec -------------------------------------------------------------
def encode_array(arr) -> dict:
    """numpy array -> msgpack-ready map.  Complex arrays are split into two
    real byte strings (msgpack has no complex type); everything else ships
    as raw C-order bytes + dtype string."""
    arr = np.ascontiguousarray(arr)
    if np.iscomplexobj(arr):
        re = np.ascontiguousarray(arr.real)
        im = np.ascontiguousarray(arr.imag)
        return {"__nd__": 1, "dtype": arr.dtype.str, "shape": list(arr.shape),
                "data": re.tobytes(), "imag": im.tobytes()}
    return {"__nd__": 1, "dtype": arr.dtype.str, "shape": list(arr.shape),
            "data": arr.tobytes()}


def decode_array(d) -> np.ndarray:
    """Inverse of :func:`encode_array` (validating: a wrong payload size for
    the declared dtype/shape raises :class:`ProtocolError`, never a numpy
    internal error)."""
    if not isinstance(d, dict) or d.get("__nd__") != 1:
        raise ProtocolError(f"not an encoded array: {type(d).__name__}")
    try:
        dtype = np.dtype(d["dtype"])
        shape = tuple(int(s) for s in d["shape"])
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    except (KeyError, TypeError, ValueError) as e:
        raise ProtocolError(f"bad array header: {e}") from None
    if dtype.kind == "c":
        half = np.dtype(f"f{dtype.itemsize // 2}")
        try:
            re = np.frombuffer(d["data"], half)
            im = np.frombuffer(d["imag"], half)
        except (KeyError, TypeError, ValueError) as e:
            raise ProtocolError(f"bad complex array payload: {e}") from None
        if re.size != n or im.size != n:
            raise ProtocolError(
                f"array payload size mismatch: {re.size}/{im.size} elements "
                f"for shape {shape}"
            )
        return (re + 1j * im).astype(dtype).reshape(shape)
    try:
        flat = np.frombuffer(d["data"], dtype)
    except (KeyError, TypeError, ValueError) as e:
        raise ProtocolError(f"bad array payload: {e}") from None
    if flat.size != n:
        raise ProtocolError(
            f"array payload size mismatch: {flat.size} elements for shape {shape}"
        )
    return flat.reshape(shape)


def _encode(obj):
    if isinstance(obj, np.ndarray):
        return encode_array(obj)
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode(v) for v in obj]
    return obj


def _decode(obj):
    if isinstance(obj, dict):
        if obj.get("__nd__") == 1:
            return decode_array(obj)
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v) for v in obj]
    return obj


# -- framing -----------------------------------------------------------------
def pack_frame(frame: dict) -> bytes:
    """One frame dict -> length-prefixed msgpack bytes."""
    payload = msgpack.packb(_encode(frame), use_bin_type=True)
    if len(payload) > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}); send smaller blocks"
        )
    return _LEN.pack(len(payload)) + payload


def unpack_payload(payload: bytes) -> dict:
    """msgpack payload bytes -> frame dict (arrays decoded)."""
    try:
        d = msgpack.unpackb(payload, raw=False, strict_map_key=False)
    except Exception as e:
        raise ProtocolError(f"bad msgpack payload: {e}") from None
    if not isinstance(d, dict) or "type" not in d:
        raise ProtocolError("frame must be a map with a 'type' key")
    return _decode(d)


def read_exact(sock: socket.socket, n: int) -> bytes | None:
    """Read exactly ``n`` bytes from a blocking socket; None on clean EOF at
    a frame boundary (EOF mid-frame raises — that is a truncated frame)."""
    chunks, got = [], 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ProtocolError(f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> dict | None:
    """Blocking read of one frame; None on clean EOF."""
    head = read_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame length {n} exceeds MAX_FRAME_BYTES")
    payload = read_exact(sock, n)
    if payload is None:
        raise ProtocolError("connection closed between length prefix and payload")
    return unpack_payload(payload)


def send_frame(sock: socket.socket, frame: dict) -> None:
    """Blocking write of one frame."""
    sock.sendall(pack_frame(frame))


def frame_header_size() -> int:
    """Byte length of the frame length-prefix header."""
    return _LEN.size
