"""Pure-NumPy streaming client for the online enhancement service.

This module (with :mod:`disco_tpu.serve.protocol`) is the whole client-side
dependency surface: **numpy + stdlib only, no jax import** — a client
process must never contend for the single tunneled chip (environment
contract; pinned by tests/test_serve.py).  A client holds one session per
connection; open several clients for several streams.

>>> client = ServeClient(("127.0.0.1", 7433))
>>> client.open(SessionConfig(n_nodes=4, mics_per_node=2, n_freq=257,
...                           block_frames=8))
>>> yf = client.enhance_clip(Y, mask_z, mask_w)   # (K, F, T) enhanced STFT
>>> client.close()

Frames from the server are demultiplexed by a reader thread, so a client
may stream blocks ahead of reading outputs (the server's admission control
bounds how far: a ``backpressure`` error frame means wait and resend).

Survival (the client half of the serving survival layer): the initial
connect retries connection-refused with bounded **seeded-jitter backoff**
(a server restart window is not an outage; the jitter desynchronizes K
clients reconnecting at once, deterministically per seed), and a session
interrupted by a dropped connection / a ``parked`` error frame
**reconnects and reattaches transparently**: the client re-opens with its
resume token and its next-needed output seq (``have``), the server replays
the deliveries it missed from the bounded replay buffer and names the next
input seq it expects, and the resend machinery (the same ``resend_from``
rollback that serves backpressure) re-sends anything the dead socket ate —
the stitched stream is bit-exact, no frame lost or duplicated.  The retry
loops here are stdlib-inline by necessity: the purity contract above bars
this module from ``disco_tpu.utils.resilience`` (whose transport-error
table imports jax), which is exactly the carve-out disco-lint rule DL013
documents.
"""
from __future__ import annotations

import queue as queue_mod
import random
import socket
import threading
import time

import numpy as np

from disco_tpu.obs import trace as obs_trace
from disco_tpu.serve import protocol
from disco_tpu.serve.session import SessionConfig


class ServeError(RuntimeError):
    """An ``error`` frame from the server (code + message), or a dead
    connection."""

    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class ServeClient:
    """One streaming session over one socket connection.

    Args:
      address: ``(host, port)`` tuple or unix-socket path.
      timeout_s: per-frame receive timeout.
      connect_retries: extra connect attempts on ``OSError`` (connection
        refused during a server restart window), with seeded-jitter
        exponential backoff.  0 restores fail-on-first-error.
      reattach_retries: automatic reconnect-and-reattach budget for a
        session interrupted mid-stream (dropped connection, ``parked``
        frame).  0 disables transparent reattach.
      retry_seed: drives every backoff jitter draw (deterministic
        schedules; give concurrent clients distinct seeds to spread their
        reconnect storm).
      trace: causal-tracing opt-in — True mints a trace/span header
        (``disco_tpu.obs.trace``, stdlib-only) per submitted block and
        rides it in the ``block`` frame so the server can thread the
        block's end-to-end span chain; False never sends one (the
        pre-span wire shape); None (default) follows the process-global
        tracer (``obs.trace.enabled()``), so enabling tracing in-process
        traces loopback clients with zero per-call-site wiring.
    """

    def __init__(self, address, timeout_s: float = 120.0, *,
                 connect_retries: int = 3,
                 connect_base_delay_s: float = 0.05,
                 reattach_retries: int = 3,
                 reattach_timeout_s: float = 15.0,
                 retry_seed: int = 0,
                 trace: bool | None = None):
        self.timeout_s = timeout_s
        self.address = address
        self.connect_retries = int(connect_retries)
        self.connect_base_delay_s = float(connect_base_delay_s)
        self.reattach_timeout_s = float(reattach_timeout_s)
        self._trace = trace
        self._reattach_left = int(reattach_retries)
        self._rng = random.Random(retry_seed)
        self.session_id: str | None = None
        self.config: SessionConfig | None = None
        self.blocks_done = 0          # server-acknowledged start block on open
        self.next_seq = 0
        self.draining = False
        self.resend_from: int | None = None   # lowest seq the server rejected
        self.closed_info: dict | None = None
        self.reattaches = 0           # completed transparent reattaches
        #: {output seq: weight generation id} for generation-tagged
        #: ``enhanced`` frames (sessions served with masks="model"); empty
        #: for classic client-mask sessions — the wire carries no tag there
        self.gen_of: dict[int, str] = {}
        self._next_expected = 0       # lowest output seq not yet received
        self._frames: "queue_mod.Queue" = queue_mod.Queue()
        self._enhanced: dict[int, np.ndarray] = {}
        self._reader: threading.Thread | None = None
        self._sock: socket.socket | None = None
        self._connect()

    # -- connection plumbing -------------------------------------------------
    def _connect(self) -> None:
        """Dial the server and start a reader thread for the new socket.

        Bounded seeded-backoff retry on ``OSError``: a client must survive
        the window where the server is restarting (connection refused), and
        K clients retrying in lockstep would all reconnect in the same
        instant — each delay is ``min(base * 2^i, 1s)`` shrunk by up to 50%
        from this client's seeded jitter stream.  (Inline stdlib retry by
        the purity contract — module docstring.)"""
        address = self.address
        family = (socket.AF_UNIX if isinstance(address, (str, bytes))
                  else socket.AF_INET)
        target = address if isinstance(address, (str, bytes)) else tuple(address)
        attempt = 0
        while True:
            sock = socket.socket(family, socket.SOCK_STREAM)
            try:
                sock.connect(target)
                break
            except OSError:
                sock.close()
                if attempt >= self.connect_retries:
                    raise
                delay = min(self.connect_base_delay_s * 2 ** attempt, 1.0)
                time.sleep(delay * (1.0 - 0.5 * self._rng.random()))
                attempt += 1
        self._sock = sock
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,), daemon=True)
        self._reader.start()

    # -- frame plumbing ------------------------------------------------------
    def _read_loop(self, sock):
        try:
            while True:
                frame = protocol.recv_frame(sock)
                if frame is None:
                    self._frames.put(None)
                    return
                self._frames.put(frame)
        except (OSError, protocol.ProtocolError) as e:
            self._frames.put(e)

    def _next_frame(self, timeout_s=None):
        try:
            item = self._frames.get(timeout=timeout_s or self.timeout_s)
        except queue_mod.Empty:
            raise ServeError("timeout", "no frame from server within timeout") from None
        if item is None:
            raise ServeError("eof", "server closed the connection")
        if isinstance(item, BaseException):
            raise ServeError("io", str(item))
        return item

    def _fold(self, frame: dict) -> None:
        """Fold one session-level frame into client state (raises for
        non-recoverable ``error`` frames)."""
        kind = frame.get("type")
        if kind == "enhanced":
            seq = int(frame["seq"])
            self._enhanced[seq] = frame["yf"]
            if frame.get("gen") is not None:
                self.gen_of[seq] = frame["gen"]
            self._next_expected = max(self._next_expected, seq + 1)
        elif kind == "draining":
            self.draining = True
        elif kind == "closed":
            self.closed_info = frame
        elif kind == "error":
            seq = frame.get("seq")
            if frame.get("code") == "backpressure" and seq is not None:
                # the server's queue bound rejected this block — recoverable:
                # remember the resend point and roll the auto-seq back so the
                # stream resumes from the rejection (docstring contract above)
                seq = int(seq)
                if self.resend_from is None or seq < self.resend_from:
                    self.resend_from = seq
                self.next_seq = min(self.next_seq, seq)
            else:
                raise ServeError(frame.get("code", "?"), frame.get("message", ""))

    def _pump(self, timeout_s=None) -> dict:
        """Read one frame, folding session-level notices into client state;
        returns the frame (callers match on ``type``).  A dropped
        connection or a ``parked`` frame triggers transparent
        reconnect-and-reattach (bounded by ``reattach_retries``)."""
        while True:
            try:
                frame = self._next_frame(timeout_s)
            except ServeError as e:
                if e.code in ("eof", "io") and self._can_reattach():
                    self._reattach(f"connection lost ({e.code})")
                    if self.closed_info is not None:
                        return self.closed_info   # finished during the drop
                    if self.resend_from is not None:
                        return {"type": "reattached",
                                "resend_from": self.resend_from}
                    continue
                raise
            if (frame.get("type") == "error"
                    and frame.get("code") == "parked"
                    and self._can_reattach()):
                self._reattach(
                    "server parked the session",
                    retry_after_s=float(frame.get("retry_after_s", 0.0)))
                if self.closed_info is not None:
                    return self.closed_info
                if self.resend_from is not None:
                    # the drop ate input blocks the server never queued:
                    # blocking for another frame would deadlock (the server
                    # is idle, waiting for the resend) — hand control back
                    # so the wait loops re-check the resend cursor
                    # (``recv_enhanced`` raises its documented
                    # ``backpressure``; ``enhance_clip`` rolls ``next_send``
                    # back and resends)
                    return {"type": "reattached",
                            "resend_from": self.resend_from}
                continue
            self._fold(frame)
            return frame

    # -- transparent reattach ------------------------------------------------
    def _can_reattach(self) -> bool:
        return (self._reattach_left > 0 and self.session_id is not None
                and self.config is not None and self.closed_info is None)

    def _reattach(self, reason: str, retry_after_s: float = 0.0) -> None:
        """Reconnect and reattach the interrupted session (docstring at
        module level describes the protocol).  Raises :class:`ServeError`
        (``reattach_failed`` or the server's rejection code) when the
        session cannot be stitched."""
        self._reattach_left -= 1
        sock, reader = self._sock, self._reader
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if reader is not None:
            reader.join(timeout=5.0)
        # the dead reader's leftovers: fold real frames (deliveries that
        # raced the drop), discard its EOF/error sentinel
        while True:
            try:
                item = self._frames.get_nowait()
            except queue_mod.Empty:
                break
            if item is None or isinstance(item, BaseException):
                continue
            if not (item.get("type") == "error"
                    and item.get("code") == "parked"):
                self._fold(item)
        if self.closed_info is not None:
            # the dead reader's leftovers included the 'closed' frame: the
            # session actually finished — there is nothing to reattach
            return
        if retry_after_s > 0:
            time.sleep(retry_after_s)
        try:
            self._connect()
        except OSError as e:
            raise ServeError(
                "reattach_failed",
                f"could not reconnect after {reason}: {e}") from None
        protocol.send_frame(self._sock, {
            "type": "open", "config": self.config.to_dict(),
            "resume": self.session_id, "have": self._next_expected,
        })
        reply = self._next_frame(timeout_s=self.reattach_timeout_s)
        if reply.get("type") == "error":
            raise ServeError(reply.get("code", "reattach_failed"),
                             reply.get("message", ""))
        if reply.get("type") != "open_ok":
            raise ServeError("reattach_failed",
                             f"expected open_ok, got {reply.get('type')!r}")
        self.blocks_done = int(reply.get("blocks_done", 0))
        server_next = int(reply.get("next_seq", self.blocks_done))
        if server_next < self.next_seq:
            # the dead socket ate input blocks [server_next, next_seq):
            # roll the resend cursor back — send_block / enhance_clip
            # re-send from there exactly like after a backpressure reject
            if self.resend_from is None or server_next < self.resend_from:
                self.resend_from = server_next
            self.next_seq = server_next
        self.reattaches += 1

    # -- session lifecycle ---------------------------------------------------
    def open(self, config: SessionConfig | dict, *, session_id: str | None = None,
             z_mask=None, resume: str | None = None) -> str:
        """Open (or resume) the session; returns the server session id."""
        cfg = config if isinstance(config, SessionConfig) else SessionConfig.from_dict(config)
        frame = {"type": "open", "config": cfg.to_dict()}
        if session_id is not None:
            frame["session"] = session_id
        if z_mask is not None:
            frame["z_mask"] = np.asarray(z_mask, np.float32)
        if resume is not None:
            frame["resume"] = resume
        protocol.send_frame(self._sock, frame)
        reply = self._pump()
        if reply.get("type") != "open_ok":
            raise ServeError("protocol", f"expected open_ok, got {reply.get('type')!r}")
        self.session_id = reply["session"]
        self.config = cfg
        self.blocks_done = int(reply.get("blocks_done", 0))
        self.next_seq = int(reply.get("next_seq", self.blocks_done))
        self._next_expected = self.blocks_done
        return self.session_id

    def _send(self, frame: dict) -> None:
        """Send one frame; a dead socket triggers reattach (bounded) and
        ONE re-send of the frame — a stale ``block`` seq after reattach is
        then corrected by the server's backpressure reply, the same
        convergence as any other resend."""
        while True:
            try:
                protocol.send_frame(self._sock, frame)
                return
            except OSError as e:
                if not self._can_reattach():
                    raise ServeError("io", f"send failed: {e}") from None
                self._reattach(f"send failed: {e}")
                if self.closed_info is not None:
                    return   # the session finished during the drop: the
                             # frame is moot, callers observe closed_info

    def send_block(self, Y, mask_z=None, mask_w=None,
                   seq: int | None = None) -> int:
        """Stream one input block; returns its seq.  ``Y`` (K, C, F, T)
        complex64, masks (K, F, T) float32; T = config.block_frames except
        for a shorter final block.  Sessions opened with
        ``SessionConfig(masks="model")`` send NO masks (the server fills
        both from its live weight generation) — pass None, the default.
        Chained sessions (``SessionConfig(domain="time")``) send float32
        (K, C, samples) time windows instead — one whole super-tick window
        per block, masks on the window's STFT grid (K, F, 1 + samples //
        (n_freq - 1)) — and receive (K, samples) enhanced float windows."""
        if self.session_id is None:
            raise ServeError("protocol", "send_block before open")
        seq = self.next_seq if seq is None else int(seq)
        if self.resend_from is not None and seq <= self.resend_from:
            self.resend_from = None      # resending from the rejection point
        wire_dtype = (np.float32
                      if self.config is not None and self.config.domain == "time"
                      else np.complex64)
        frame = {
            "type": "block", "seq": seq,
            "Y": np.ascontiguousarray(Y, dtype=wire_dtype),
            "mask_z": (None if mask_z is None
                       else np.ascontiguousarray(mask_z, dtype=np.float32)),
            "mask_w": (None if mask_w is None
                       else np.ascontiguousarray(mask_w, dtype=np.float32)),
        }
        if self._trace or (self._trace is None and obs_trace.enabled()):
            # mint the causal root at submission: the client_block span is
            # the chain's origin, and the wire header lets the server
            # thread every later hop under it (a resend of the same seq
            # after backpressure/reattach mints a fresh trace — honest:
            # it IS a new submission).  With the process-global tracer off
            # (explicit trace=True in a bare client process) the ids are
            # minted without a local span event — the server-side chain
            # then starts at its enqueue hop, by design.
            ctx = obs_trace.root("client_block", seq=seq,
                                 session=self.session_id)
            if ctx is None:
                ctx = obs_trace.SpanCtx(trace=obs_trace.new_id(),
                                        span=obs_trace.new_id())
            frame["trace"] = ctx.to_wire()
        self._send(frame)
        self.next_seq = seq + 1
        return seq

    def recv_enhanced(self, seq: int, timeout_s=None) -> np.ndarray:
        """Block until the enhanced output for ``seq`` arrives.

        Raises a ``backpressure`` :class:`ServeError` if the server rejected
        ``seq`` (or an earlier block) — the output would never arrive;
        resend from :attr:`resend_from` (``send_block`` with ``seq=None``
        already rolls back there) and call again."""
        while seq not in self._enhanced:
            if self.resend_from is not None and self.resend_from <= seq:
                raise ServeError(
                    "backpressure",
                    f"block {self.resend_from} was rejected by the server's "
                    f"queue bound; resend from seq {self.resend_from} before "
                    f"waiting on {seq}",
                )
            self._pump(timeout_s)
        return self._enhanced.pop(seq)

    def close(self, timeout_s=None) -> dict:
        """Finish the session: ask the server to flush, wait for the
        ``closed`` frame.  Returns its payload (``blocks_done``,
        ``state_path`` when the server checkpointed)."""
        if self.session_id is None:
            raise ServeError("protocol", "close before open")
        frame = {"type": "close", "session": self.session_id}
        self._send(frame)
        sent_gen = self.reattaches
        while self.closed_info is None:
            self._pump(timeout_s)
            if self.reattaches != sent_gen:
                # a reattach happened since the close frame went out: the
                # reattached (OPEN again) session never saw it — re-send,
                # or the wait below outlives the server's memory of it
                self._send(frame)
                sent_gen = self.reattaches
        return self.closed_info

    def status(self, timeout_s=None) -> dict:
        """Read-only live introspection: send one ``status`` frame, return
        the server's ``status_ok`` payload.  Works with or without an open
        session; session-level frames that arrive first are folded into
        client state as usual."""
        self._send({"type": "status"})
        while True:
            frame = self._next_frame(timeout_s)
            if frame.get("type") == "status_ok":
                return frame
            self._fold(frame)

    def wait_closed(self, timeout_s=None) -> dict:
        """Wait for a server-initiated close (a drain) without sending
        anything — collects stray enhanced frames on the way."""
        while self.closed_info is None:
            self._pump(timeout_s)
        return self.closed_info

    def shutdown(self) -> None:
        self._reattach_left = 0   # a deliberate teardown must stay torn down
        if self._sock is None:
            return
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    # -- convenience ---------------------------------------------------------
    def enhance_clip(self, Y, mask_z=None, mask_w=None, *, window: int = 4,
                     on_block=None) -> np.ndarray:
        """Stream a whole (K, C, F, T) clip through the open session and
        return the (K, F, T) enhanced STFT.

        Blocks of ``config.block_frames`` frames are kept at most
        ``window`` in flight (sending everything first would trip the
        server's queue bound on long clips); a ``backpressure`` rejection
        (a window wider than the server's ``max_queue_blocks``) rolls the
        send cursor back and the rejected blocks are resent once outputs
        drain the queue.  Starts at the session's ``blocks_done``
        (resume-aware).  ``on_block(seq, yf)`` observes each output as it
        lands.
        """
        if self.config is None:
            raise ServeError("protocol", "enhance_clip before open")
        T = Y.shape[-1]
        Tb = self.config.block_frames
        n_blocks = -(-T // Tb)
        outs: dict[int, np.ndarray] = {}
        start = self.blocks_done
        if start >= n_blocks:
            # resumed checkpoint already covers the whole clip: nothing to
            # stream, nothing to return (the earlier blocks were delivered
            # to the pre-resume client)
            return np.zeros(
                (self.config.n_nodes, self.config.n_freq, 0), np.complex64
            )
        next_send = start
        next_recv = start
        while next_recv < n_blocks:
            if self.resend_from is not None and self.resend_from < next_send:
                next_send = self.resend_from
            while next_send < n_blocks and next_send - next_recv < window:
                lo, hi = next_send * Tb, min((next_send + 1) * Tb, T)
                self.send_block(
                    Y[..., lo:hi],
                    None if mask_z is None else mask_z[..., lo:hi],
                    None if mask_w is None else mask_w[..., lo:hi],
                    seq=next_send)
                next_send += 1
            if next_recv in self._enhanced:
                yf = self._enhanced.pop(next_recv)
                outs[next_recv] = yf
                if on_block is not None:
                    on_block(next_recv, yf)
                next_recv += 1
                continue
            self._pump()
        return np.concatenate([outs[i] for i in range(start, n_blocks)], axis=-1)
