"""Pure-NumPy streaming client for the online enhancement service.

This module (with :mod:`disco_tpu.serve.protocol`) is the whole client-side
dependency surface: **numpy + stdlib only, no jax import** — a client
process must never contend for the single tunneled chip (environment
contract; pinned by tests/test_serve.py).  A client holds one session per
connection; open several clients for several streams.

>>> client = ServeClient(("127.0.0.1", 7433))
>>> client.open(SessionConfig(n_nodes=4, mics_per_node=2, n_freq=257,
...                           block_frames=8))
>>> yf = client.enhance_clip(Y, mask_z, mask_w)   # (K, F, T) enhanced STFT
>>> client.close()

Frames from the server are demultiplexed by a reader thread, so a client
may stream blocks ahead of reading outputs (the server's admission control
bounds how far: a ``backpressure`` error frame means wait and resend).
"""
from __future__ import annotations

import queue as queue_mod
import socket
import threading

import numpy as np

from disco_tpu.serve import protocol
from disco_tpu.serve.session import SessionConfig


class ServeError(RuntimeError):
    """An ``error`` frame from the server (code + message), or a dead
    connection."""

    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class ServeClient:
    """One streaming session over one socket connection."""

    def __init__(self, address, timeout_s: float = 120.0):
        self.timeout_s = timeout_s
        self._sock = socket.socket(
            socket.AF_UNIX if isinstance(address, (str, bytes)) else socket.AF_INET,
            socket.SOCK_STREAM,
        )
        self._sock.connect(address if isinstance(address, (str, bytes)) else tuple(address))
        self.session_id: str | None = None
        self.config: SessionConfig | None = None
        self.blocks_done = 0          # server-acknowledged start block on open
        self.next_seq = 0
        self.draining = False
        self.resend_from: int | None = None   # lowest seq the server rejected
        self.closed_info: dict | None = None
        self._frames: "queue_mod.Queue" = queue_mod.Queue()
        self._enhanced: dict[int, np.ndarray] = {}
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # -- frame plumbing ------------------------------------------------------
    def _read_loop(self):
        try:
            while True:
                frame = protocol.recv_frame(self._sock)
                if frame is None:
                    self._frames.put(None)
                    return
                self._frames.put(frame)
        except (OSError, protocol.ProtocolError) as e:
            self._frames.put(e)

    def _next_frame(self, timeout_s=None):
        try:
            item = self._frames.get(timeout=timeout_s or self.timeout_s)
        except queue_mod.Empty:
            raise ServeError("timeout", "no frame from server within timeout") from None
        if item is None:
            raise ServeError("eof", "server closed the connection")
        if isinstance(item, BaseException):
            raise ServeError("io", str(item))
        return item

    def _pump(self, timeout_s=None) -> dict:
        """Read one frame, folding session-level notices into client state;
        returns the frame (callers match on ``type``)."""
        frame = self._next_frame(timeout_s)
        kind = frame.get("type")
        if kind == "enhanced":
            self._enhanced[int(frame["seq"])] = frame["yf"]
        elif kind == "draining":
            self.draining = True
        elif kind == "closed":
            self.closed_info = frame
        elif kind == "error":
            seq = frame.get("seq")
            if frame.get("code") == "backpressure" and seq is not None:
                # the server's queue bound rejected this block — recoverable:
                # remember the resend point and roll the auto-seq back so the
                # stream resumes from the rejection (docstring contract above)
                seq = int(seq)
                if self.resend_from is None or seq < self.resend_from:
                    self.resend_from = seq
                self.next_seq = min(self.next_seq, seq)
            else:
                raise ServeError(frame.get("code", "?"), frame.get("message", ""))
        return frame

    # -- session lifecycle ---------------------------------------------------
    def open(self, config: SessionConfig | dict, *, session_id: str | None = None,
             z_mask=None, resume: str | None = None) -> str:
        """Open (or resume) the session; returns the server session id."""
        cfg = config if isinstance(config, SessionConfig) else SessionConfig.from_dict(config)
        frame = {"type": "open", "config": cfg.to_dict()}
        if session_id is not None:
            frame["session"] = session_id
        if z_mask is not None:
            frame["z_mask"] = np.asarray(z_mask, np.float32)
        if resume is not None:
            frame["resume"] = resume
        protocol.send_frame(self._sock, frame)
        reply = self._pump()
        if reply.get("type") != "open_ok":
            raise ServeError("protocol", f"expected open_ok, got {reply.get('type')!r}")
        self.session_id = reply["session"]
        self.config = cfg
        self.blocks_done = int(reply.get("blocks_done", 0))
        self.next_seq = self.blocks_done
        return self.session_id

    def send_block(self, Y, mask_z, mask_w, seq: int | None = None) -> int:
        """Stream one input block; returns its seq.  ``Y`` (K, C, F, T)
        complex64, masks (K, F, T) float32; T = config.block_frames except
        for a shorter final block."""
        if self.session_id is None:
            raise ServeError("protocol", "send_block before open")
        seq = self.next_seq if seq is None else int(seq)
        if self.resend_from is not None and seq <= self.resend_from:
            self.resend_from = None      # resending from the rejection point
        protocol.send_frame(self._sock, {
            "type": "block", "seq": seq,
            "Y": np.ascontiguousarray(Y, dtype=np.complex64),
            "mask_z": np.ascontiguousarray(mask_z, dtype=np.float32),
            "mask_w": np.ascontiguousarray(mask_w, dtype=np.float32),
        })
        self.next_seq = seq + 1
        return seq

    def recv_enhanced(self, seq: int, timeout_s=None) -> np.ndarray:
        """Block until the enhanced output for ``seq`` arrives.

        Raises a ``backpressure`` :class:`ServeError` if the server rejected
        ``seq`` (or an earlier block) — the output would never arrive;
        resend from :attr:`resend_from` (``send_block`` with ``seq=None``
        already rolls back there) and call again."""
        while seq not in self._enhanced:
            if self.resend_from is not None and self.resend_from <= seq:
                raise ServeError(
                    "backpressure",
                    f"block {self.resend_from} was rejected by the server's "
                    f"queue bound; resend from seq {self.resend_from} before "
                    f"waiting on {seq}",
                )
            self._pump(timeout_s)
        return self._enhanced.pop(seq)

    def close(self, timeout_s=None) -> dict:
        """Finish the session: ask the server to flush, wait for the
        ``closed`` frame.  Returns its payload (``blocks_done``,
        ``state_path`` when the server checkpointed)."""
        if self.session_id is None:
            raise ServeError("protocol", "close before open")
        protocol.send_frame(self._sock, {"type": "close", "session": self.session_id})
        while self.closed_info is None:
            self._pump(timeout_s)
        return self.closed_info

    def wait_closed(self, timeout_s=None) -> dict:
        """Wait for a server-initiated close (a drain) without sending
        anything — collects stray enhanced frames on the way."""
        while self.closed_info is None:
            self._pump(timeout_s)
        return self.closed_info

    def shutdown(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    # -- convenience ---------------------------------------------------------
    def enhance_clip(self, Y, mask_z, mask_w, *, window: int = 4,
                     on_block=None) -> np.ndarray:
        """Stream a whole (K, C, F, T) clip through the open session and
        return the (K, F, T) enhanced STFT.

        Blocks of ``config.block_frames`` frames are kept at most
        ``window`` in flight (sending everything first would trip the
        server's queue bound on long clips); a ``backpressure`` rejection
        (a window wider than the server's ``max_queue_blocks``) rolls the
        send cursor back and the rejected blocks are resent once outputs
        drain the queue.  Starts at the session's ``blocks_done``
        (resume-aware).  ``on_block(seq, yf)`` observes each output as it
        lands.
        """
        if self.config is None:
            raise ServeError("protocol", "enhance_clip before open")
        T = Y.shape[-1]
        Tb = self.config.block_frames
        n_blocks = -(-T // Tb)
        outs: dict[int, np.ndarray] = {}
        start = self.blocks_done
        if start >= n_blocks:
            # resumed checkpoint already covers the whole clip: nothing to
            # stream, nothing to return (the earlier blocks were delivered
            # to the pre-resume client)
            return np.zeros(
                (self.config.n_nodes, self.config.n_freq, 0), np.complex64
            )
        next_send = start
        next_recv = start
        while next_recv < n_blocks:
            if self.resend_from is not None and self.resend_from < next_send:
                next_send = self.resend_from
            while next_send < n_blocks and next_send - next_recv < window:
                lo, hi = next_send * Tb, min((next_send + 1) * Tb, T)
                self.send_block(Y[..., lo:hi], mask_z[..., lo:hi], mask_w[..., lo:hi],
                                seq=next_send)
                next_send += 1
            if next_recv in self._enhanced:
                yf = self._enhanced.pop(next_recv)
                outs[next_recv] = yf
                if on_block is not None:
                    on_block(next_recv, yf)
                next_recv += 1
                continue
            self._pump()
        return np.concatenate([outs[i] for i in range(start, n_blocks)], axis=-1)
