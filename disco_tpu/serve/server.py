"""The online enhancement server: asyncio I/O + one dispatch thread.

The environment contract allows exactly ONE chip-claiming process, so
concurrency cannot come from worker processes: all socket I/O runs on an
asyncio event loop (its own thread), and ALL device work runs on a single
dispatch thread driving :meth:`~disco_tpu.serve.scheduler.Scheduler.tick`
— the only thread that ever enters jax.  Connections hand blocks to the
scheduler through thread-safe session queues; deliveries come back through
``loop.call_soon_threadsafe`` onto per-connection writer queues.

Lifecycle (the production seams of PR 2–4, wired in unchanged):

* ``preflight`` — the CLI runs :func:`~disco_tpu.utils.resilience.
  preflight_probe` before binding the socket, so a wedged attachment fails
  in seconds, not after clients connect.
* graceful interruption — the dispatch loop polls
  :func:`disco_tpu.runs.interrupt.stop_requested` between ticks: the first
  SIGINT/SIGTERM stops admitting sessions, notifies every client
  (``draining`` frame), finishes every queued block, checkpoints the live
  sessions (``--state-dir``; atomic msgpack + digest,
  :func:`~disco_tpu.serve.session.save_session_state`) and closes them
  with a ``closed`` frame naming ``blocks_done`` + the checkpoint path —
  zero truncated or lost frames, and every stream resumable.
* chaos — the ``serve_tick`` seam fires at every tick; an injected
  :class:`~disco_tpu.runs.chaos.ChaosCrash` unwinds the dispatch thread
  like a process death (connections drop, nothing more is written) and is
  re-raised to the embedding caller by :meth:`EnhanceServer.wait`.

One session per connection; a client wanting N concurrent streams opens N
connections (they still share the one device through the scheduler —
that is the whole point).
"""
from __future__ import annotations

import asyncio
import contextlib
import itertools
import socket
import threading
import time

from disco_tpu.obs import events as obs_events
from disco_tpu.serve import protocol
from disco_tpu.serve.scheduler import (
    DEFAULT_MAX_BLOCKS_PER_TICK,
    QueueFull,
    Scheduler,
)
from disco_tpu.serve.session import CLOSED, DRAINING, EVICTED, OPEN, PARKED, QUARANTINED

#: Writer-queue bound per connection: a client that stops reading while the
#: scheduler keeps producing gets evicted (with a clean ``error`` frame)
#: once this many frames are backed up — bounded host memory per client.
DEFAULT_MAX_BACKLOG = 64


class _Conn:
    """Per-connection bookkeeping shared between the I/O and dispatch
    threads (the queue crossing happens via call_soon_threadsafe)."""

    _born = itertools.count()

    def __init__(self):
        self.session = None
        self.outq: asyncio.Queue | None = None
        self.notified_draining = False
        self.closed_sent = False
        #: creation order: after a park+reattach two conns can briefly
        #: reference one session — deliveries go to the newest live one
        self.born = next(_Conn._born)
        #: the posting cursor: next output seq this connection is owed.
        #: ONLY the dispatch loop advances it, draining the session's
        #: replay buffer — one poster thread, so a reattach's replay can
        #: never race an in-flight delivery into a duplicate or a loss.
        #: None until a session is attached (the I/O thread sets it BEFORE
        #: ``session``, which is the dispatch loop's gate).
        self.next_out: int | None = None


class EnhanceServer:
    """Embeddable server: ``start()`` binds and spins the loop + dispatch
    threads, ``stop()`` drains gracefully, ``wait()`` joins (re-raising a
    dispatch-thread crash).  The CLI's :meth:`serve_forever` adds the
    signal story on top."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 unix_path: str | None = None,
                 scheduler: Scheduler | None = None,
                 max_sessions: int = 16, max_queue_blocks: int = 8,
                 max_blocks_per_tick: int = DEFAULT_MAX_BLOCKS_PER_TICK,
                 blocks_per_super_tick: int = 1,
                 overlap_readback: bool | None = None,
                 allow_chained: bool = True,
                 max_backlog: int = DEFAULT_MAX_BACKLOG,
                 tick_interval_s: float = 0.002,
                 state_dir=None, fault_spec=None, tap=None,
                 park_on_disconnect: bool = True,
                 park_ttl_s: float = 60.0,
                 replay_blocks: int = 64,
                 dispatch_retries: int = 2,
                 retry_seed: int = 0,
                 tick_deadline_s: float | None = None,
                 quarantine_ticks: int = 20,
                 ladder=None,
                 sock_sndbuf: int | None = None,
                 write_buffer_high: int | None = None,
                 promote=None,
                 resident=None,
                 run_info: dict | None = None):
        self.host, self.port, self.unix_path = host, port, unix_path
        if ladder is True:
            from disco_tpu.serve.ladder import DegradationLadder

            ladder = DegradationLadder()
        elif not ladder:
            ladder = None   # False/None both mean: no overload controller
        self.scheduler = scheduler or Scheduler(
            max_sessions=max_sessions, max_queue_blocks=max_queue_blocks,
            max_blocks_per_tick=max_blocks_per_tick,
            blocks_per_super_tick=blocks_per_super_tick,
            overlap_readback=overlap_readback, allow_chained=allow_chained,
            fault_spec=fault_spec, tap=tap,
            park_ttl_s=park_ttl_s, replay_blocks=replay_blocks,
            dispatch_retries=dispatch_retries, retry_seed=retry_seed,
            tick_deadline_s=tick_deadline_s,
            quarantine_ticks=quarantine_ticks,
            ladder=ladder, state_dir=state_dir, promote=promote,
            resident=resident,
        )
        #: optional PromotionController — started/stopped with the server
        #: (its thread never enters jax; swaps execute on the dispatch
        #: thread).  A pre-built scheduler brings its own.
        self.promote = (promote if promote is not None
                        else getattr(self.scheduler, "promote", None))
        #: optional co-resident trainer — stepped by the scheduler at the
        #: tail of every tick (dispatch thread), closed when the server
        #: stops.  A pre-built scheduler brings its own.
        self.resident = (resident if resident is not None
                         else getattr(self.scheduler, "resident", None))
        #: connection drops / mid-frame protocol truncations PARK the
        #: session (resume token, bounded TTL, bit-exact reattach) instead
        #: of evicting; False restores the old evict-on-drop behavior
        self.park_on_disconnect = park_on_disconnect
        self.max_backlog = max_backlog
        #: bandwidth shaping for tests/drills: SO_SNDBUF applied to every
        #: accepted socket, and the asyncio transport's write high-water
        #: mark.  ``max_backlog`` only meters frames the writer could not
        #: flush, so proving the slow-client eviction path needs a pipe
        #: that actually jams — with both set small, drain() blocks as
        #: soon as the peer stops reading instead of whenever the kernel's
        #: autotuned buffers happen to fill.  None (default) = untouched.
        self.sock_sndbuf = sock_sndbuf
        self.write_buffer_high = write_buffer_high
        self.tick_interval_s = tick_interval_s
        self.state_dir = state_dir
        #: extra attrs folded into the ``run_start`` event (the CLI rides
        #: its preflight result and knob settings here)
        self.run_info = dict(run_info or {})
        self.address = None            # (host, port) or unix path once bound
        self.crashed: BaseException | None = None
        self.checkpoints: dict = {}    # {session_id: state path} after a drain
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server = None
        self._loop_thread: threading.Thread | None = None
        self._dispatch_thread: threading.Thread | None = None
        self._stop_event = threading.Event()      # programmatic drain trigger
        self._started = threading.Event()
        self._conns: set[_Conn] = set()
        self._conns_lock = threading.Lock()

    # -- connection handling (asyncio thread) --------------------------------
    async def _read_frame(self, reader: asyncio.StreamReader):
        try:
            head = await reader.readexactly(protocol.frame_header_size())
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        n = int.from_bytes(head, "big")
        if n > protocol.MAX_FRAME_BYTES:
            raise protocol.ProtocolError(f"frame length {n} exceeds MAX_FRAME_BYTES")
        try:
            payload = await reader.readexactly(n)
        except (asyncio.IncompleteReadError, ConnectionError):
            raise protocol.ProtocolError("connection closed mid-frame") from None
        return protocol.unpack_payload(payload)

    async def _writer_task(self, conn: _Conn, writer: asyncio.StreamWriter):
        try:
            while True:
                item = await conn.outq.get()
                if item is None:
                    break
                writer.write(item)
                await writer.drain()
        except (ConnectionError, RuntimeError):
            pass

    def _post(self, conn: _Conn, frame: dict) -> None:
        """Queue one frame for a connection (any thread).  Evicts the
        session instead of growing without bound when the client is not
        draining its socket."""
        data = protocol.pack_frame(frame)
        loop, outq = self._loop, conn.outq
        if loop is None or outq is None or loop.is_closed():
            return
        if frame.get("type") == "enhanced" and conn.session is not None:
            if conn.session.status == EVICTED:
                return   # already evicted this session: drop stale deliveries
            if outq.qsize() >= self.max_backlog:
                self.scheduler.evict(conn.session, "slow client: output backlog "
                                     f"exceeded max_backlog={self.max_backlog}")
                err = protocol.pack_frame({
                    "type": "error", "code": "evicted",
                    "message": f"evicted: {conn.session.error}",
                    "session": conn.session.id,
                })
                with contextlib.suppress(RuntimeError):
                    loop.call_soon_threadsafe(outq.put_nowait, err)
                    loop.call_soon_threadsafe(outq.put_nowait, None)
                return
        with contextlib.suppress(RuntimeError):
            loop.call_soon_threadsafe(outq.put_nowait, data)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        if self.sock_sndbuf is not None:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                self.sock_sndbuf)
        if self.write_buffer_high is not None:
            writer.transport.set_write_buffer_limits(
                high=self.write_buffer_high)
        conn = _Conn()
        conn.outq = asyncio.Queue()
        with self._conns_lock:
            self._conns.add(conn)
        wtask = asyncio.ensure_future(self._writer_task(conn, writer))
        try:
            while True:
                try:
                    frame = await self._read_frame(reader)
                except protocol.ProtocolError as e:
                    # a mid-frame truncation must never corrupt the stream:
                    # the partial block never reached push_block, so parking
                    # here (resume token in the error frame) lets the client
                    # reattach and RESEND it — bit-exact, nothing torn
                    if self._park(conn.session, f"protocol error: {e}"):
                        self._post(conn, self._parked_frame(
                            conn.session, f"protocol error: {e}"))
                    else:
                        self._post(conn, {"type": "error", "code": "protocol",
                                          "message": str(e)})
                    break
                if frame is None:
                    break
                if not self._on_frame(conn, frame):
                    break
                if conn.closed_sent:
                    break
        finally:
            if (conn.session is not None
                    and conn.session.status not in (CLOSED, EVICTED, PARKED)
                    and not self._park(conn.session, "connection dropped")):
                # connection died with a live session and parking is off
                # (or raced a close): free the slot the old way
                self.scheduler.evict(conn.session, "connection closed")
            with self._conns_lock:
                self._conns.discard(conn)
            # end-of-stream sentinel goes through the same call_soon path as
            # every frame, so it can never overtake a just-posted error
            self._post_end(conn)
            with contextlib.suppress(Exception):
                await asyncio.wait_for(wtask, timeout=5.0)
            with contextlib.suppress(Exception):
                writer.close()

    def _park(self, session, reason: str) -> bool:
        """Park a live session on connection trouble (I/O thread); False
        when parking is off or the session already left the registry."""
        if session is None or not self.park_on_disconnect:
            return False
        if session.status not in (OPEN, DRAINING, QUARANTINED):
            return False
        return self.scheduler.park(session, reason)

    def _parked_frame(self, session, reason: str,
                      retry_after_s: float = 0.0) -> dict:
        """The ``parked`` error frame: carries the resume token the client
        reattaches with (``open`` + ``resume``/``have``) and a back-off
        hint for shed sessions."""
        return {"type": "error", "code": "parked",
                "message": f"session parked: {reason}; reattach with the "
                           f"resume token within the park TTL",
                "session": session.id, "resume": session.id,
                "retry_after_s": float(retry_after_s)}

    def _on_frame(self, conn: _Conn, frame: dict) -> bool:
        """Handle one client frame (asyncio thread).  Returns False to end
        the connection."""
        kind = frame.get("type")
        if kind == "status":
            # read-only live introspection: allowed before (or without) an
            # open session, never touches jax — session states, ladder
            # rung, counters/gauges, latency percentiles and in-flight
            # spans, all host-side reads under their own locks (the
            # ``disco-obs top`` / ``slo`` surface)
            from disco_tpu.serve.status import status_payload

            self._post(conn, {"type": "status_ok",
                              **status_payload(self.scheduler)})
            return True
        if kind == "open":
            if conn.session is not None:
                self._post(conn, {"type": "error", "code": "protocol",
                                  "message": "session already open on this connection"})
                return False
            resume = frame.get("resume")
            resume_path = None
            if resume is not None:
                # a PARKED session reattaches in place: same carry, same
                # queue, missed deliveries replayed from the bounded buffer
                # — the stream stitches bit-exact with no frame lost or
                # duplicated.  Only when nothing is parked under the token
                # do we fall through to the checkpoint-resume path (which
                # also serves parked sessions of a PREVIOUS server process,
                # via the park checkpoint).
                have = frame.get("have")   # None = fresh client, plain resume
                try:
                    hit = self.scheduler.reattach(
                        resume, frame.get("config"), have)
                    if hit is None and self.park_on_disconnect:
                        # the client reconnected FASTER than the dead
                        # connection's teardown parked the session (both
                        # run on this I/O thread, so the check is
                        # race-free): park it now and reattach — the
                        # resume token is authoritative, newest
                        # connection wins
                        live = self.scheduler.get(resume)
                        if (live is not None
                                and live.status in (OPEN, DRAINING,
                                                    QUARANTINED)):
                            self.scheduler.park(
                                live, "reattach raced the disconnect")
                            hit = self.scheduler.reattach(
                                resume, frame.get("config"), have)
                except Exception as e:
                    code = getattr(e, "code", "bad_open")
                    self._post(conn, {"type": "error", "code": code,
                                      "message": str(e)})
                    return False
                if hit is not None:
                    session, resume_seq = hit
                    with self._conns_lock:
                        for c in self._conns:
                            if c is not conn and c.session is session:
                                c.session = None   # detach the dead conn
                    # cursor BEFORE session: session is the dispatch
                    # loop's gate, and the loop (not this thread) re-sends
                    # the missed frames from the replay buffer
                    conn.next_out = resume_seq
                    conn.session = session
                    self._post(conn, {
                        "type": "open_ok", "session": session.id,
                        "blocks_done": session.blocks_done,
                        "next_seq": session.blocks_in, "reattached": True,
                    })
                    if self.scheduler.draining:
                        self._notify_draining(conn)
                    return True
                if self.state_dir is None:
                    self._post(conn, {"type": "error", "code": "no_state_dir",
                                      "message": "server has no --state-dir; cannot resume"})
                    return False
                from pathlib import Path

                resume_path = Path(self.state_dir) / f"session_{resume}.state.msgpack"
                if not resume_path.is_file():
                    self._post(conn, {"type": "error", "code": "unknown_session",
                                      "message": f"no checkpoint for session {resume!r}"})
                    return False
            try:
                conn.session = self.scheduler.open_session(
                    frame.get("config"),
                    session_id=frame.get("session") or resume,
                    z_mask=frame.get("z_mask"),
                    resume_from=resume_path,
                    priority=bool(frame.get("priority", False)),
                )
            except Exception as e:  # AdmissionError carries .code; rest default
                code = getattr(e, "code", "bad_open")
                self._post(conn, {"type": "error", "code": code, "message": str(e)})
                return False
            conn.next_out = conn.session.blocks_done
            self._post(conn, {"type": "open_ok", "session": conn.session.id,
                              "blocks_done": conn.session.blocks_done,
                              "next_seq": conn.session.blocks_in})
            if self.scheduler.draining:
                # admitted in the race window right before draining flipped
                self._notify_draining(conn)
            return True
        if conn.session is None:
            self._post(conn, {"type": "error", "code": "protocol",
                              "message": f"{kind!r} before 'open'"})
            return False
        if kind == "block":
            try:
                self.scheduler.push_block(
                    conn.session, int(frame.get("seq", -1)),
                    frame.get("Y"), frame.get("mask_z"), frame.get("mask_w"),
                    trace=frame.get("trace"),
                )
            except QueueFull as e:
                self._post(conn, {"type": "error", "code": "backpressure",
                                  "message": str(e), "session": conn.session.id,
                                  "seq": frame.get("seq")})
            except Exception as e:
                self._post(conn, {"type": "error", "code": "bad_block",
                                  "message": f"{type(e).__name__}: {e}",
                                  "session": conn.session.id})
                return False
            return True
        if kind == "close":
            self.scheduler.request_close(conn.session)
            return True
        self._post(conn, {"type": "error", "code": "protocol",
                          "message": f"unknown frame type {kind!r}"})
        return False

    def _notify_draining(self, conn: _Conn) -> None:
        if conn.session is not None and not conn.notified_draining:
            conn.notified_draining = True
            self._post(conn, {"type": "draining", "session": conn.session.id})

    # -- dispatch loop (its own thread; the only jax thread) -----------------
    def _dispatch_loop(self):
        from disco_tpu.runs.interrupt import stop_requested

        try:
            while True:
                stopping = self._stop_event.is_set() or stop_requested()
                if stopping and not self.scheduler.draining:
                    obs_events.record("interrupted", stage="serve",
                                      reason="drain requested")
                    self.scheduler.start_drain()
                    with self._conns_lock:
                        conns = list(self._conns)
                    for conn in conns:
                        self._notify_draining(conn)
                deliveries = self.scheduler.tick()
                self._post_enhanced()
                for session, reason, retry_after in \
                        self.scheduler.drain_park_notices():
                    # shed-to-park happened on the dispatch thread with the
                    # connection still up: name it to the client (resume
                    # token + back-off hint), then end the stream
                    conn = self._conn_of(session)
                    if conn is None:
                        continue
                    conn.closed_sent = True
                    self._post(conn, self._parked_frame(
                        session, reason, retry_after_s=retry_after))
                    self._post_end(conn)
                self._flush_finished()
                if self.scheduler.draining and self.scheduler.pending_blocks() == 0:
                    self._drain_finish()
                    return
                if not deliveries:
                    time.sleep(self.tick_interval_s)
        except BaseException as e:  # ChaosCrash included: simulated death
            self.crashed = e  # disco-race: disable=DR007 -- wait() reads the stash only after join() proves this thread dead (and clears it on the caller thread); the join is the happens-before edge a lock would duplicate
            self._shutdown_loop()

    def _post_enhanced(self) -> None:
        """Post every connection's owed ``enhanced`` frames (dispatch
        thread — the ONE poster).  Frames are drained from the session's
        replay buffer through the per-conn cursor, so a delivery landing
        mid-reattach is posted exactly once: either the cursor was set
        before it landed (the loop picks it up next pass) or after (the
        cursor starts past it) — never both, never neither."""
        from disco_tpu.serve.session import SessionStateError

        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            s, nxt = conn.session, conn.next_out
            if s is None or nxt is None or conn.closed_sent:
                continue
            if s.blocks_done <= nxt:
                continue
            try:
                entries = s.replay_from(nxt)
            except SessionStateError as e:
                # the cursor fell behind the bounded buffer — impossible
                # while this loop keeps up (it drains every pass), kept as
                # a refuse-to-corrupt guard rather than a silent hole
                self.scheduler.evict(s, f"replay cursor gap: {e}")
                continue
            for seq, yf in entries:
                if conn.session is not s or s.status == EVICTED:
                    break   # evicted mid-drain (slow client) / detached
                frame = {"type": "enhanced", "session": s.id,
                         "seq": int(seq), "yf": yf}
                if s.generation is not None:
                    # which weight generation enhanced this block — only
                    # generation-tracked sessions carry the key, so a
                    # promote-less server's wire stays bit-identical
                    frame["gen"] = s.gen_for(seq)
                self._post(conn, frame)
                conn.next_out = seq + 1

    def _conn_of(self, session) -> _Conn | None:
        with self._conns_lock:
            best = None
            for conn in self._conns:
                if conn.session is session and not conn.closed_sent:
                    # after a reattach two conns can briefly share a session
                    # (the dead one not torn down yet): newest wins
                    if best is None or conn.born > best.born:
                        best = conn
            return best

    def _flush_finished(self) -> None:
        """Send ``closed`` frames for sessions the scheduler finished (close
        requested + queue drained) this tick."""
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            s = conn.session
            if s is None or conn.closed_sent:
                continue
            if s.status == CLOSED:
                conn.closed_sent = True
                self._post(conn, {"type": "closed", "session": s.id,
                                  "blocks_done": s.blocks_done})
                self._post_end(conn)
            elif s.status == EVICTED and s.error != "connection closed":
                conn.closed_sent = True
                # name the eviction before the stream ends (the slow-client
                # path already posted one; its writer is gone by now, so a
                # duplicate never reaches the socket)
                self._post(conn, {"type": "error", "code": "evicted",
                                  "message": f"evicted: {s.error}",
                                  "session": s.id})
                self._post_end(conn)

    def _post_end(self, conn: _Conn) -> None:
        loop, outq = self._loop, conn.outq
        if loop is not None and outq is not None and not loop.is_closed():
            with contextlib.suppress(RuntimeError):
                loop.call_soon_threadsafe(outq.put_nowait, None)

    def _drain_finish(self) -> None:
        """All queues empty under drain: checkpoint live sessions, close
        every stream with its resume coordinates, stop the loop."""
        if self.state_dir is not None:
            self.checkpoints = self.scheduler.checkpoint_sessions(self.state_dir)
        else:
            self.checkpoints = {}
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            s = conn.session
            if s is None or conn.closed_sent:
                continue
            conn.closed_sent = True
            # drained == closed: everything was delivered and checkpointed,
            # so the connection teardown must not read this as a live
            # session and record a spurious evict
            s.status = CLOSED
            self._post(conn, {
                "type": "closed", "session": s.id, "blocks_done": s.blocks_done,
                "resumable": s.id in self.checkpoints,
                "state_path": self.checkpoints.get(s.id),
            })
            self._post_end(conn)
        obs_events.record(
            "session", stage="serve", action="drain",
            n_checkpointed=len(self.checkpoints),
        )
        self._shutdown_loop(grace_s=2.0)

    def _shutdown_loop(self, grace_s: float = 0.0) -> None:
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        if grace_s:
            # let writer tasks flush the closing frames before the loop dies
            deadline = time.perf_counter() + grace_s
            while time.perf_counter() < deadline:
                with self._conns_lock:
                    busy = any(c.outq is not None and c.outq.qsize() > 0
                               for c in self._conns)
                if not busy:
                    break
                time.sleep(0.01)
        with contextlib.suppress(RuntimeError):
            loop.call_soon_threadsafe(loop.stop)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Bind and start serving; returns the bound address ((host, port)
        tuple, or the unix socket path)."""
        self._loop = asyncio.new_event_loop()

        async def _bind():
            if self.unix_path is not None:
                # a previous server's socket file survives its process (unix
                # sockets are not unlinked on close) and would fail the bind
                # with EADDRINUSE; clear it ONLY if it really is a socket
                import os
                import stat

                try:
                    if stat.S_ISSOCK(os.stat(self.unix_path).st_mode):
                        os.unlink(self.unix_path)
                except FileNotFoundError:
                    pass
                self._server = await asyncio.start_unix_server(
                    self._handle, path=str(self.unix_path))
                self.address = str(self.unix_path)
            else:
                self._server = await asyncio.start_server(
                    self._handle, host=self.host, port=self.port)
                self.address = self._server.sockets[0].getsockname()[:2]

        def _run():
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(_bind())
            self._started.set()
            self._loop.run_forever()
            # loop stopped: close the listener FIRST (a stopped server must
            # refuse connections, not accept into a void — clients' connect
            # retries need the refusal to find the next server), then cancel
            # whatever is left and close
            if self._server is not None:
                self._server.close()
            for task in asyncio.all_tasks(self._loop):
                task.cancel()
            with contextlib.suppress(Exception):
                self._loop.run_until_complete(
                    asyncio.gather(*asyncio.all_tasks(self._loop),
                                   return_exceptions=True))
            self._loop.close()

        self._loop_thread = threading.Thread(
            target=_run, name="disco-serve-io", daemon=True)
        self._loop_thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("serve: event loop failed to start")
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="disco-serve-dispatch", daemon=True)
        self._dispatch_thread.start()
        if self.promote is not None:
            # resume-then-run: an interrupted rollout is settled from the
            # ledger BEFORE any session can open against a torn state
            self.promote.start()
        obs_events.record("run_start", stage="serve", tool="disco-serve",
                          address=str(self.address), **self.run_info)
        return self.address

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful drain from the embedding caller: finish queued blocks,
        checkpoint, close streams, stop threads.  Raises the dispatch
        thread's crash, if any (a chaos-injected death must surface)."""
        self._stop_event.set()
        if self.promote is not None:
            self.promote.stop()
        self.wait(timeout_s)

    def wait(self, timeout_s: float | None = None) -> None:
        """Join the dispatch thread (and then the loop thread), re-raising
        a crash from either tick or drain."""
        if self._dispatch_thread is not None:
            self._dispatch_thread.join(timeout_s)
            if self._dispatch_thread.is_alive():
                raise TimeoutError("serve: dispatch thread did not stop in time")
        if self._loop_thread is not None:
            self._loop_thread.join(5.0)
        if self.promote is not None:
            self.promote.stop()
            self.promote.wait(timeout_s=5.0)
        if self.resident is not None:
            # the dispatch thread (its only stepper) is dead by here, so
            # the flag-only close cannot race a running slice
            self.resident.close()
        if self.crashed is not None:
            crash, self.crashed = self.crashed, None
            raise crash

    def serve_forever(self) -> None:
        """The CLI loop: serve until the first SIGINT/SIGTERM, then drain
        (the :class:`~disco_tpu.runs.interrupt.GracefulInterrupt` scope is
        installed by the CLI around this call)."""
        self.start()
        if isinstance(self.address, tuple):
            print(f"disco-serve listening on {self.address[0]}:{self.address[1]}")
        else:
            print(f"disco-serve listening on {self.address}")
        self.wait()
