"""Continuous-batching scheduler: many concurrent sessions, one device.

Each tick gathers the ready blocks across all live sessions and runs them
as one device batch in the sense that matters on this hardware: every
block's :func:`~disco_tpu.enhance.streaming.streaming_tango` step is
dispatched *asynchronously* (no readback between sessions — dispatches
queue on device), and the tick's outputs cross the host boundary in ONE
complex-safe :func:`~disco_tpu.utils.transfer.device_get_tree` — the same
discipline as the corpus engine (``enhance/pipeline.fetch_chunk_host``),
where the fixed ~80 ms RPC per fenced readback, not per-op compute, is the
cost model (CLAUDE.md).  ``device_get_batches`` therefore advances exactly
once per tick-with-work, which is what ``make serve-check`` asserts.

Why not one vmapped megabatch: a vmapped program compiles *different
fusions* than the offline per-clip program, and the warm-up GEVD refreshes
run on near-degenerate covariances where a one-ulp covariance difference
flips the ``ffill`` hold guard and diverges the whole stream — measured at
~1.0 relative error on synthetic CPU streams.  Per-session dispatch through
the **same jitted callable the offline path uses** makes serve output
bit-identical to ``streaming_tango`` by construction (the serve-check
parity gate), while the *shape bucket* — sessions sharing a
:class:`~disco_tpu.serve.session.SessionConfig` — still bounds compiles to
one program per bucket via the jit cache (``counted_jit`` makes any drift
visible as ``jit_trace`` events).  Off-CPU the step re-jits the same
function with the carry donated (``donate_argnames=("state",)``): identical
HLO math, buffers reused in place — the corpus engine's donation rule.

Super-ticks (``blocks_per_super_tick`` = N > 1) amortize the fenced RPC
further: every run of N consecutive full queued blocks a session
contributes to a tick rides ONE scanned program
(:func:`~disco_tpu.enhance.streaming.streaming_tango_scan` — the per-block
state transition under a fully-unrolled ``lax.scan``, bit-identical by
construction), and the double-buffered tick state overlaps tick T+1's
dispatch with tick T's batched readback.  Sub-N remainders and ragged final
blocks fall back to the per-block path, bounding compiles to two programs
per shape bucket.

Admission control is first-class: a bounded session count
(``admission_reject`` counter), a bounded per-session input queue
(backpressure errors instead of unbounded host memory), and slow-client
eviction hooks (``session_evicted``).  Telemetry: ``sessions_active`` /
``queue_depth`` / ``batch_occupancy`` gauges and the
``serve_block_latency_ms`` histogram, all rendered by ``disco-obs report``.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.serve.session import (
    CLOSED,
    DRAINING,
    EVICTED,
    OPEN,
    Session,
    SessionConfig,
    load_session_state,
)

#: Default bound on blocks enhanced per tick across all sessions — keeps
#: one tick's device queue (and its single readback payload) bounded, so a
#: bursty client cannot starve the others for a whole tick.
DEFAULT_MAX_BLOCKS_PER_TICK = 64

#: Refresh-block horizon of a per-session fault plan drawn from a server
#: ``--fault-spec`` (``plan_faults`` needs a concrete width; blocks past
#: the horizon are treated as delivered).
FAULT_PLAN_BLOCKS = 4096


class AdmissionError(RuntimeError):
    """Session rejected at the door (capacity, draining, bad config)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class QueueFull(RuntimeError):
    """Per-session input queue bound hit — backpressure, not a crash."""


_STEPS: dict = {}
_STEP_LOCK = threading.Lock()

_STEP_STATICS = ("update_every", "ref_mic", "with_diagnostics", "policy", "solver")


def _resolve_step(attr: str, label: str, extra_static=()):
    """The ONE step-resolution discipline, lazily cached per entry point.

    CPU: literally the offline jitted wrapper (``enhance.streaming.<attr>``)
    itself, so serve and offline share one compiled program per shape
    bucket and parity is true by construction.  Off-CPU: a ``counted_jit``
    of the same underlying function with the continuation carry donated
    (aliasing metadata only — the HLO math is unchanged).
    """
    step = _STEPS.get(attr)
    if step is None:
        with _STEP_LOCK:
            if attr not in _STEPS:
                import jax

                from disco_tpu.enhance import streaming
                from disco_tpu.obs.accounting import counted_jit

                wrapper = getattr(streaming, attr)
                if jax.default_backend() == "cpu":
                    _STEPS[attr] = wrapper
                else:
                    _STEPS[attr] = counted_jit(
                        wrapper.__wrapped__,
                        label=label,
                        static_argnames=tuple(extra_static) + _STEP_STATICS,
                        donate_argnames=("state",),
                    )
            step = _STEPS[attr]
    return step


def _serve_step():
    """The per-block step callable (see :func:`_resolve_step`)."""
    return _resolve_step("streaming_tango", "serve_step")


def _serve_scan_step():
    """The super-tick step callable: the scanned multi-block driver
    (:func:`~disco_tpu.enhance.streaming.streaming_tango_scan`), resolved
    with exactly the :func:`_serve_step` discipline (shared program per
    (shape bucket, N) on CPU, donated carry off-CPU)."""
    return _resolve_step("streaming_tango_scan", "serve_scan_step",
                         extra_static=("blocks_per_dispatch",))


class Scheduler:
    """Session registry + the per-tick continuous-batching loop body.

    Thread model: ``open_session`` / ``push_block`` / ``request_close`` are
    called from the server's I/O thread; :meth:`tick` runs on the single
    dispatch thread (the ONLY place jax is entered — one chip claim per
    process, per the environment contract).  The registry lock is never
    held across device work.
    """

    def __init__(self, *, max_sessions: int = 16, max_queue_blocks: int = 8,
                 max_blocks_per_tick: int = DEFAULT_MAX_BLOCKS_PER_TICK,
                 blocks_per_super_tick: int = 1,
                 overlap_readback: bool | None = None,
                 fault_spec=None, tap=None):
        if max_sessions < 1 or max_queue_blocks < 1 or max_blocks_per_tick < 1:
            raise ValueError("scheduler bounds must be >= 1")
        if blocks_per_super_tick < 1:
            raise ValueError("blocks_per_super_tick must be >= 1")
        if blocks_per_super_tick > max_blocks_per_tick:
            # no group of N could ever form inside the tick budget — the
            # knob would be silently inert (same fail-at-startup rule as
            # the --max-blocks-per-tick plumbing fix in PR 5)
            raise ValueError(
                f"blocks_per_super_tick={blocks_per_super_tick} exceeds "
                f"max_blocks_per_tick={max_blocks_per_tick}: no super-tick "
                "could ever form"
            )
        self.max_sessions = max_sessions
        self.max_queue_blocks = max_queue_blocks
        self.max_blocks_per_tick = max_blocks_per_tick
        #: N: every run of N consecutive full queued blocks of a session is
        #: dispatched as ONE scanned super-tick program
        #: (streaming_tango_scan) — one fenced readback share per N blocks.
        #: The sub-N remainder (and a ragged final block) falls back to the
        #: per-block path, so exactly two programs exist per shape bucket
        #: (per-block + N-scan) and the last partial window never waits for
        #: more input.
        self.blocks_per_super_tick = blocks_per_super_tick
        #: Double-buffered tick state: when on, tick T dispatches its work
        #: FIRST and then reads back tick T-1's batch, so the device computes
        #: super-tick T while the host drains super-tick T-1's readback (the
        #: pipeline.py overlap pattern applied to serving).  Deliveries lag
        #: one tick; an idle tick flushes the buffer.  Default: on exactly
        #: when super-ticks are on.
        self.overlap_readback = (blocks_per_super_tick > 1
                                 if overlap_readback is None else overlap_readback)
        self.fault_spec = fault_spec
        #: opt-in flywheel corpus tap (disco_tpu.flywheel.CorpusTap), fed at
        #: the post-readback seam with every delivered block's host arrays
        #: (noisy Y, masks, enhanced yf).  The tap's offer() never blocks
        #: and never raises — overflow drops-and-counts inside the tap —
        #: so serving cannot backpressure or crash on its own telemetry.
        self.tap = tap
        self.draining = False
        self._lock = threading.Lock()
        self._sessions: dict[str, Session] = {}
        self._session_seq = 0
        self._rotate = 0
        self.ticks_with_work = 0
        #: dispatched-but-not-read-back units from the previous tick
        #: (overlap_readback):
        #: [(session, [seq, ...], yf_device, t_dispatch, raw_blocks)] where
        #: raw_blocks keeps the input (seq, Y, mz, mw) host tuples for the
        #: corpus tap (None when no tap — no point pinning the memory)
        self._inflight: list = []

    # -- registry (I/O thread) ----------------------------------------------
    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions.values())

    def get(self, session_id: str) -> Session | None:
        with self._lock:
            return self._sessions.get(session_id)

    def open_session(self, config, *, session_id: str | None = None,
                     z_mask=None, resume_from=None) -> Session:
        """Admit one session (or resume a checkpointed one).

        Raises :class:`AdmissionError` on capacity / draining / config
        problems — the server turns those into clean ``error`` frames.
        """
        if self.draining:
            obs_registry.counter("admission_reject").inc()
            raise AdmissionError("draining", "server is draining; not admitting sessions")
        if not isinstance(config, SessionConfig):
            try:
                config = SessionConfig.from_dict(config)
            except ValueError as e:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError("bad_config", str(e)) from None

        with self._lock:
            if len(self._sessions) >= self.max_sessions:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "capacity",
                    f"server at max_sessions={self.max_sessions}; retry later",
                )
            self._session_seq += 1
            seq = self._session_seq

        if resume_from is not None:
            session = load_session_state(resume_from)
            if session.config != config:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "config_mismatch",
                    f"checkpoint {resume_from} was made with a different "
                    f"session config; resume with the original one",
                )
            if session_id is not None:
                session.id = session_id
        else:
            from disco_tpu.enhance.streaming import initial_stream_state

            sid = session_id or f"s{seq:06d}"
            z_avail = self._session_fault_plan(config, seq, z_mask)
            session = Session(
                sid, config,
                z_avail=z_avail,
                state=initial_stream_state(
                    config.n_nodes, config.mics_per_node, config.n_freq,
                    update_every=config.update_every, ref_mic=config.ref_mic,
                ),
            )
        with self._lock:
            if session.id in self._sessions:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "duplicate", f"session id {session.id!r} already live"
                )
            self._sessions[session.id] = session
        obs_events.record(
            "session", stage="serve", action="open", session=session.id,
            resumed_blocks=session.blocks_done,
            faulted=session.z_avail is not None,
        )
        self._set_gauges()
        return session

    def _session_fault_plan(self, config: SessionConfig, seq: int, z_mask):
        """Per-session z availability: an explicit client mask wins; else a
        server fault spec is expanded per session (seeded off the admission
        sequence number, so every session draws its own deterministic
        realization — ablation runs reproduce exactly)."""
        if z_mask is not None:
            mask = np.asarray(z_mask, np.float32)
            if mask.shape not in ((config.n_nodes,),) and (
                mask.ndim != 2 or mask.shape[0] != config.n_nodes
            ):
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "bad_config",
                    f"z_mask shape {mask.shape} does not match n_nodes={config.n_nodes}",
                )
            return mask
        if self.fault_spec is None or not self.fault_spec.any_fault():
            return None
        import dataclasses

        from disco_tpu.fault.inject import plan_faults

        spec = dataclasses.replace(self.fault_spec, seed=self.fault_spec.seed + seq)
        plan = plan_faults(spec, config.n_nodes, n_blocks=FAULT_PLAN_BLOCKS)
        plan.record(mode="serve")
        if not plan.any_fault():
            return None
        return np.asarray(plan.avail_streaming, np.float32)

    def push_block(self, session: Session, seq: int, Y, mask_z, mask_w) -> None:
        """Accept one input block (I/O thread).  Validates shape/order and
        enforces the queue bound (:class:`QueueFull` = backpressure)."""
        cfg = session.config
        if session.status not in (OPEN, DRAINING):
            raise QueueFull(f"session {session.id} is {session.status}")
        if seq != session.blocks_in:
            raise QueueFull(
                f"out-of-order block seq {seq} (expected {session.blocks_in}); "
                "blocks must arrive in order"
            )
        Y = np.asarray(Y)
        if not np.issubdtype(Y.dtype, np.number):
            # the wire codec round-trips ANY declared dtype; a non-numeric
            # block must die here as a bad_block, not inside the dispatch
            # thread (where it would read as a server crash)
            raise ValueError(f"block Y dtype {Y.dtype} is not numeric")
        exp = cfg.block_shape
        if Y.shape[:-1] != exp[:-1] or Y.shape[-1] > exp[-1] or Y.shape[-1] < 1:
            raise QueueFull(
                f"block shape {Y.shape} does not fit session shape {exp} "
                "(only the final block may be shorter)"
            )
        for name, m in (("mask_z", mask_z), ("mask_w", mask_w)):
            m = np.asarray(m)  # disco-lint: disable=DL002 -- wire-decoded host arrays on the I/O thread; no device array can reach push_block
            if not np.issubdtype(m.dtype, np.number):
                raise ValueError(f"{name} dtype {m.dtype} is not numeric")
            if m.shape != (cfg.n_nodes, cfg.n_freq, Y.shape[-1]):
                raise QueueFull(f"{name} shape {m.shape} does not match block {Y.shape}")
        if session.queue_depth() >= self.max_queue_blocks:
            raise QueueFull(
                f"session {session.id} input queue at max_queue_blocks="
                f"{self.max_queue_blocks}; wait for enhanced blocks"
            )
        session.push_block(seq, Y, np.asarray(mask_z), np.asarray(mask_w), time.time())
        self._set_gauges()

    def request_close(self, session: Session) -> None:
        session.close_requested = True

    def evict(self, session: Session, reason: str) -> None:
        """Drop a session that is not keeping up (unread output backlog,
        dead connection).  The server sends the clean ``error`` frame; this
        records the decision and frees the slot."""
        with self._lock:
            self._sessions.pop(session.id, None)
        session.status = EVICTED
        session.error = reason
        obs_registry.counter("session_evicted").inc()
        obs_events.record("session", stage="serve", action="evict",
                          session=session.id, reason=reason)
        self._set_gauges()

    def _finish(self, session: Session) -> None:
        with self._lock:
            self._sessions.pop(session.id, None)
        session.status = CLOSED
        obs_events.record("session", stage="serve", action="close",
                          session=session.id, blocks=session.blocks_done)
        self._set_gauges()

    # -- dispatch (scheduler thread) ----------------------------------------
    def tick(self) -> list:
        """One continuous-batching step.

        Returns ``[(session, seq, yf, latency_s), ...]`` host-side
        deliveries (``yf`` numpy complex64), plus finishes sessions whose
        close was requested and whose queues (and in-flight dispatches)
        drained.  Exactly one batched readback per tick that reads work
        back; none on an idle tick.  With super-ticks on
        (``blocks_per_super_tick`` = N > 1), each session's popped blocks
        ride scanned dispatches in groups of N (the sub-N remainder goes
        per-block), and with ``overlap_readback``
        the readback of the previous tick's batch happens *after* this
        tick's dispatches are queued — the device computes super-tick T+1
        while the host reads super-tick T.
        """
        from disco_tpu.runs import chaos

        chaos.tick("serve_tick")
        sessions = self.sessions()
        if sessions:
            # rotate the starting session each tick: under sustained overload
            # the per-tick block budget runs out, and a fixed registry order
            # would starve the sessions at the tail indefinitely
            k = self._rotate % len(sessions)
            self._rotate += 1
            sessions = sessions[k:] + sessions[:k]
        units: list = []  # (session, [seq, ...], yf_device, t_dispatch, raw)
        keep_raw = self.tap is not None
        budget = self.max_blocks_per_tick
        n_super = self.blocks_per_super_tick
        n_busy = 0
        t0 = time.perf_counter()
        for session in sessions:
            if session.status not in (OPEN, DRAINING) or budget <= 0:
                continue
            if n_super > 1:
                # align the pop to a multiple of N: a deeper-than-budget
                # queue must never shed a sub-N remainder through per-block
                # dispatches every tick just because max_blocks_per_tick
                # isn't a multiple of N — blocks left queued join the next
                # tick's scan group instead.  A sub-N *queue* (stream tail /
                # starved input) still pops in full below and rides the
                # per-block fallback.  When the budget remainder is < N
                # (later sessions of a crowded tick), skip — the per-tick
                # rotation hands this session a full-width slot next tick.
                cap = budget // n_super * n_super
                if cap == 0:
                    continue
            else:
                cap = budget
            blocks = session.pop_blocks(cap)
            if not blocks:
                continue
            n_busy += 1
            budget -= len(blocks)
            bf = session.config.block_frames
            try:
                # every run of N consecutive full blocks rides one scanned
                # dispatch; the sub-N remainder (or a group holding the
                # ragged final block — always the stream's last) goes
                # per-block, so a deep queue amortizes at the same 1-fence-
                # per-N rate as an exactly-N one (the scanned program only
                # ever sees N full refresh-aligned blocks).
                for g in range(0, len(blocks), n_super):
                    group = blocks[g:g + n_super]
                    if (n_super > 1 and len(group) == n_super
                            and all(b[1].shape[-1] == bf for b in group)):
                        yf = self._dispatch_scan(session, group)
                        units.append(
                            (session, [b[0] for b in group], yf, time.time(),
                             group if keep_raw else None)
                        )
                        session.inflight += len(group)
                    else:
                        for seq, Y, mz, mw in group:
                            yf = self._dispatch(session, seq, Y, mz, mw)
                            units.append(
                                (session, [seq], yf, time.time(),
                                 [(seq, Y, mz, mw)] if keep_raw else None)
                            )
                            session.inflight += 1
            except Exception as e:
                # per-session isolation: one block the device rejects
                # (validation can't anticipate every jax TypeError) must
                # not unwind the dispatch thread and kill every other
                # live session — evict the offender and keep serving.
                # ChaosCrash is a BaseException and still dies here.
                self.evict(
                    session, f"dispatch failed: {type(e).__name__}: {e}"
                )

        if self.overlap_readback:
            # double buffer: read back the PREVIOUS tick's batch while this
            # tick's dispatches compute; an idle tick flushes the buffer
            to_read, self._inflight = self._inflight, units
        else:
            to_read = units
        deliveries = self._readback(to_read) if to_read else []
        if to_read:
            obs_registry.histogram("serve_tick_ms").observe(
                (time.perf_counter() - t0) * 1e3
            )
        obs_registry.gauge("batch_occupancy").set(
            n_busy / self.max_sessions if self.max_sessions else 0.0
        )

        for session in sessions:
            if (session.close_requested and session.status in (OPEN, DRAINING)
                    and session.queue_depth() == 0 and session.inflight == 0):
                self._finish(session)
        self._set_gauges()
        return deliveries

    def _readback(self, units: list) -> list:
        """ONE batched readback over ``units`` and the per-block delivery
        bookkeeping.  A super-tick unit's (K, F, N*block_frames) output is
        split back into its N per-seq blocks host-side (pure slicing — the
        scanned program computed them back to back along the frame axis).

        The ``serve_block_latency_ms`` total is split into its two
        components so super-tick tuning is observable:
        ``serve_queue_wait_ms`` (enqueue → dispatch: admission wait) and
        ``serve_dispatch_ms`` (dispatch → host delivery: device time plus
        the fenced readback share — and, with ``overlap_readback`` on, the
        deliberate one-tick buffering lag; the two components always sum to
        the total, so the delivery cost of the overlap is charged here, not
        hidden).
        """
        from disco_tpu.utils.transfer import device_get_tree

        n_blocks = sum(len(seqs) for (_, seqs, _, _, _) in units)
        n_sessions = len({s.id for (s, _, _, _, _) in units})
        with obs_events.stage("serve_tick", n_blocks=n_blocks,
                              n_sessions=n_sessions):
            host = device_get_tree([yf for (_, _, yf, _, _) in units])
        now = time.time()
        lat_hist = obs_registry.histogram("serve_block_latency_ms")
        wait_hist = obs_registry.histogram("serve_queue_wait_ms")
        disp_hist = obs_registry.histogram("serve_dispatch_ms")
        deliveries = []
        for (session, seqs, _, t_disp, raw), yf in zip(units, host):
            bf = session.config.block_frames
            for j, seq in enumerate(seqs):
                blk = yf if len(seqs) == 1 else yf[..., j * bf:(j + 1) * bf]
                t_in = session.enqueued_at.pop(seq, None)
                lat_s = (now - t_in) if t_in is not None else 0.0
                lat_hist.observe(lat_s * 1e3)
                if t_in is not None:
                    wait_hist.observe(max(t_disp - t_in, 0.0) * 1e3)
                disp_hist.observe(max(now - t_disp, 0.0) * 1e3)
                session.blocks_done = max(session.blocks_done, seq + 1)
                session.inflight = max(session.inflight - 1, 0)
                deliveries.append((session, seq, blk, lat_s))
            if self.tap is not None and raw:
                # THE corpus-tap seam: every delivered block's full training
                # tuple is host-resident right here (inputs were retained at
                # dispatch, yf just crossed in the one batched readback).
                # offer() is non-blocking and exception-free by contract.
                # Super-tick slices are COPIED before spooling: a queued
                # view would pin the whole N-block readback buffer and
                # void the tap queue's memory bound under backlog.
                for j, (seq, Y, mz, mw) in enumerate(raw):
                    blk = (yf if len(seqs) == 1
                           else np.ascontiguousarray(yf[..., j * bf:(j + 1) * bf]))
                    self.tap.offer(session.id, seq, Y, mz, mw, blk)
        self.ticks_with_work += 1
        obs_registry.counter("serve_ticks").inc()
        obs_registry.counter("serve_blocks").inc(n_blocks)
        if any(len(seqs) > 1 for (_, seqs, _, _, _) in units):
            obs_registry.counter("serve_super_ticks").inc()
        return deliveries

    def _dispatch(self, session: Session, seq: int, Y, mz, mw):
        """Queue one block's streaming step on device (async — no
        readback).  The call goes through the exact offline entry point
        with the session's carry; only ``out["yf"]`` is fetched later, but
        the whole program (z exchange, hold, both steps) runs as offline."""
        import jax

        from disco_tpu.utils.transfer import to_device

        from disco_tpu.enhance.streaming import _float_kw

        cfg = session.config
        u = cfg.update_every
        n_refresh = -(-Y.shape[-1] // u)  # ceil: ragged final block
        step = _serve_step()
        state = jax.tree_util.tree_map(to_device, session.state)
        # lambda_cor / mu are traced floats: jax.jit folds an OMITTED default
        # at trace time but traces a PASSED value — same number, different
        # program, and the warm-up GEVD refreshes amplify the last-ulp
        # difference (see streaming.DEFAULT_LAMBDA_COR).  _float_kw is the
        # one canonical implementation of "pass only when non-default".
        kw = _float_kw(cfg.lambda_cor, cfg.mu)
        out = step(
            to_device(np.ascontiguousarray(Y)),
            to_device(np.ascontiguousarray(mz)),
            to_device(np.ascontiguousarray(mw)),
            update_every=u,
            ref_mic=cfg.ref_mic,
            policy=cfg.policy,
            state=state,
            solver=cfg.solver,
            z_avail=session.block_z_avail(seq, n_refresh),
            **kw,
        )
        session.state = out["state"]
        return out["yf"]

    def _dispatch_scan(self, session: Session, blocks: list):
        """Queue one super-tick on device: N contiguous full blocks through
        the scanned program (async — no readback).  Identical calling
        convention to :meth:`_dispatch` — same carry, same per-refresh-block
        availability columns (the scan slices them back into exactly the
        per-block chunks), same traced-float discipline — so the result is
        bit-identical to N per-block dispatches (the stream-check gate)."""
        import jax

        from disco_tpu.utils.transfer import to_device

        from disco_tpu.enhance.streaming import _float_kw

        cfg = session.config
        u = cfg.update_every
        Y = np.concatenate([np.ascontiguousarray(b[1]) for b in blocks], axis=-1)
        mz = np.concatenate([np.ascontiguousarray(b[2]) for b in blocks], axis=-1)
        mw = np.concatenate([np.ascontiguousarray(b[3]) for b in blocks], axis=-1)
        n_refresh = Y.shape[-1] // u  # grouped blocks are full: exact
        step = _serve_scan_step()
        state = jax.tree_util.tree_map(to_device, session.state)
        kw = _float_kw(cfg.lambda_cor, cfg.mu)
        out = step(
            to_device(Y),
            to_device(mz),
            to_device(mw),
            update_every=u,
            ref_mic=cfg.ref_mic,
            policy=cfg.policy,
            state=state,
            solver=cfg.solver,
            z_avail=session.block_z_avail(blocks[0][0], n_refresh),
            blocks_per_dispatch=len(blocks),
            **kw,
        )
        session.state = out["state"]
        return out["yf"]

    def pending_blocks(self) -> int:
        """Blocks not yet delivered: queued plus dispatched-in-flight (the
        drain gate must wait for the overlap buffer to flush before the
        final checkpoint, so checkpoints land on delivered-block
        boundaries)."""
        return sum(s.queue_depth() + s.inflight for s in self.sessions())

    def _set_gauges(self) -> None:
        with self._lock:
            n = len(self._sessions)
            depth = sum(s.queue_depth() for s in self._sessions.values())
        obs_registry.gauge("sessions_active").set(n)
        obs_registry.gauge("queue_depth").set(depth)

    # -- drain / checkpoint (dispatch thread) --------------------------------
    def checkpoint_sessions(self, state_dir) -> dict:
        """Checkpoint every live session's carry under ``state_dir`` —
        states fetched in ONE batched readback, files placed atomically
        (:func:`~disco_tpu.serve.session.save_session_state`).  Returns
        {session_id: path}."""
        from pathlib import Path

        from disco_tpu.serve.session import fetch_state_host, save_session_state

        state_dir = Path(state_dir)
        sessions = [s for s in self.sessions() if s.status in (OPEN, DRAINING)]
        if not sessions:
            return {}
        host_states = fetch_state_host({s.id: s.state for s in sessions})
        paths = {}
        for s in sessions:
            path = state_dir / f"session_{s.id}.state.msgpack"
            save_session_state(path, s, state_host=host_states[s.id])
            paths[s.id] = str(path)
        return paths

    def start_drain(self) -> None:
        """Stop admitting; mark every live session draining (their queued
        blocks still run to completion on subsequent ticks)."""
        self.draining = True
        for s in self.sessions():
            if s.status == OPEN:
                s.status = DRAINING
