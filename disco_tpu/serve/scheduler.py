"""Continuous-batching scheduler: many concurrent sessions, one device.

Each tick gathers the ready blocks across all live sessions and runs them
as one device batch in the sense that matters on this hardware: every
block's :func:`~disco_tpu.enhance.streaming.streaming_tango` step is
dispatched *asynchronously* (no readback between sessions — dispatches
queue on device), and the tick's outputs cross the host boundary in ONE
complex-safe :func:`~disco_tpu.utils.transfer.device_get_tree` — the same
discipline as the corpus engine (``enhance/pipeline.fetch_chunk_host``),
where the fixed ~80 ms RPC per fenced readback, not per-op compute, is the
cost model (CLAUDE.md).  ``device_get_batches`` therefore advances exactly
once per tick-with-work, which is what ``make serve-check`` asserts.

Why not one vmapped megabatch: a vmapped program compiles *different
fusions* than the offline per-clip program, and the warm-up GEVD refreshes
run on near-degenerate covariances where a one-ulp covariance difference
flips the ``ffill`` hold guard and diverges the whole stream — measured at
~1.0 relative error on synthetic CPU streams.  Per-session dispatch through
the **same jitted callable the offline path uses** makes serve output
bit-identical to ``streaming_tango`` by construction (the serve-check
parity gate), while the *shape bucket* — sessions sharing a
:class:`~disco_tpu.serve.session.SessionConfig` — still bounds compiles to
one program per bucket via the jit cache (``counted_jit`` makes any drift
visible as ``jit_trace`` events).  Off-CPU the step re-jits the same
function with the carry donated (``donate_argnames=("state",)``): identical
HLO math, buffers reused in place — the corpus engine's donation rule.

Super-ticks (``blocks_per_super_tick`` = N > 1) amortize the fenced RPC
further: every run of N consecutive full queued blocks a session
contributes to a tick rides ONE scanned program
(:func:`~disco_tpu.enhance.streaming.streaming_tango_scan` — the per-block
state transition under a fully-unrolled ``lax.scan``, bit-identical by
construction), and the double-buffered tick state overlaps tick T+1's
dispatch with tick T's batched readback.  Sub-N remainders and ragged final
blocks fall back to the per-block path, bounding compiles to two programs
per shape bucket.

Admission control is first-class: a bounded session count
(``admission_reject`` counter), a bounded per-session input queue
(backpressure errors instead of unbounded host memory), and slow-client
eviction hooks (``session_evicted``).  Telemetry: ``sessions_active`` /
``queue_depth`` / ``batch_occupancy`` gauges and the
``serve_block_latency_ms`` histogram, all rendered by ``disco-obs report``.

The serving survival layer (the third leg after PR 2's z-exchange fault
tolerance and PR 3's crash safety) lives at this tick loop's seams:

* **transport-aware dispatch** — every per-session dispatch and the tick's
  batched readback go through ``utils.resilience.call_with_retries``
  (``TRANSPORT_ERRORS`` only, seeded-jitter backoff): a transient tunnel
  RPC error retries in place instead of evicting an innocent session; a
  non-transport error keeps today's evict-with-clean-error-frame shape;
  an *exhausted* transport budget re-queues the undispatched blocks (the
  carry never advanced — a later retry is bit-identical) and moves the
  session to **quarantine** (``QUARANTINED``: skipped by the tick loop for
  ``quarantine_ticks``, re-opened after; repeat offenders are evicted).
* **dispatch deadline** — a host-only ``DispatchDeadline`` watchdog bounds
  each tick's dispatch+readback wall time; on expiry the tick is marked
  suspect, the device is fenced via ``preflight_probe`` (a sick attachment
  unwinds cleanly — never SIGKILL), and the deadline hit feeds the ladder.
* **session parking** — a dropped connection parks the session (bounded
  TTL, ``sessions_parked`` gauge, checkpointed through the atomic
  ``save_session_state`` path on the next tick) instead of evicting;
  delivered outputs land in a bounded per-session **replay buffer** so a
  reattaching client stitches the stream bit-exact with zero lost or
  duplicated frames.
* **degradation ladder** — :class:`~disco_tpu.serve.ladder.
  DegradationLadder` steps through declared rungs (per-block dispatch →
  tap off → shed-to-park) from queue-wait p95 and deadline hits, fully
  deterministic given the metric trace.
"""
from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from disco_tpu.obs import events as obs_events
from disco_tpu.obs import flight as obs_flight
from disco_tpu.obs import trace as obs_trace
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.serve.session import (
    CLOSED,
    DRAINING,
    EVICTED,
    OPEN,
    PARKED,
    QUARANTINED,
    Session,
    SessionConfig,
    SessionStateError,
    load_session_state,
)

#: Default bound on blocks enhanced per tick across all sessions — keeps
#: one tick's device queue (and its single readback payload) bounded, so a
#: bursty client cannot starve the others for a whole tick.
DEFAULT_MAX_BLOCKS_PER_TICK = 64

#: Refresh-block horizon of a per-session fault plan drawn from a server
#: ``--fault-spec`` (``plan_faults`` needs a concrete width; blocks past
#: the horizon are treated as delivered).
FAULT_PLAN_BLOCKS = 4096


class AdmissionError(RuntimeError):
    """Session rejected at the door (capacity, draining, bad config)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class QueueFull(RuntimeError):
    """Per-session input queue bound hit — backpressure, not a crash."""


#: The fakeable dispatch seam of the soak gate: when set, called as
#: ``injector(session_id, seqs)`` at the top of every dispatch attempt
#: (INSIDE the retry wrapper, so each retry re-consults it) and may raise a
#: transport error — which is how ``disco_tpu/runs/soak.py`` and the
#: regression tests exercise the retry/quarantine machinery on CPU without
#: a flaky tunnel.  Never set in production.
_DISPATCH_FAULT_INJECTOR = None


def set_dispatch_fault_injector(fn) -> None:
    """Install (or clear, with ``None``) the dispatch fault injector above.

    No reference counterpart: a pure test/soak seam (module docstring)."""
    global _DISPATCH_FAULT_INJECTOR
    _DISPATCH_FAULT_INJECTOR = fn


_STEPS: dict = {}
_STEP_LOCK = threading.Lock()

_STEP_STATICS = ("update_every", "ref_mic", "with_diagnostics", "policy", "solver")


def _resolve_step(attr: str, label: str, extra_static=(), statics=None):
    """The ONE step-resolution discipline, lazily cached per entry point.

    CPU: literally the offline jitted wrapper itself — resolved from
    ``enhance.streaming``, falling back to ``enhance.fused`` for the
    chained lane's twin — so serve and offline share one compiled program
    per shape bucket and parity is true by construction.  Off-CPU: a
    ``counted_jit`` of the same underlying function with the continuation
    carry donated (aliasing metadata only — the HLO math is unchanged).
    ``statics`` overrides the default ``extra_static + _STEP_STATICS``
    off-CPU static set for entry points whose signature differs from the
    per-block streaming step's (jit rejects static names absent from the
    wrapped signature).
    """
    step = _STEPS.get(attr)
    if step is None:
        with _STEP_LOCK:
            if attr not in _STEPS:
                import jax

                from disco_tpu.enhance import fused, streaming
                from disco_tpu.obs.accounting import counted_jit

                wrapper = getattr(streaming, attr, None)
                if wrapper is None:
                    wrapper = getattr(fused, attr)
                if jax.default_backend() == "cpu":
                    _STEPS[attr] = wrapper
                else:
                    _STEPS[attr] = counted_jit(
                        wrapper.__wrapped__,
                        label=label,
                        static_argnames=(tuple(statics) if statics is not None
                                         else tuple(extra_static) + _STEP_STATICS),
                        donate_argnames=("state",),
                    )
            step = _STEPS[attr]
    return step


def _serve_step():
    """The per-block step callable (see :func:`_resolve_step`)."""
    return _resolve_step("streaming_tango", "serve_step")


def _serve_scan_step():
    """The super-tick step callable: the scanned multi-block driver
    (:func:`~disco_tpu.enhance.streaming.streaming_tango_scan`), resolved
    with exactly the :func:`_serve_step` discipline (shared program per
    (shape bucket, N) on CPU, donated carry off-CPU)."""
    return _resolve_step("streaming_tango_scan", "serve_scan_step",
                         extra_static=("blocks_per_dispatch",))


def _serve_chained_step():
    """The chained-lane step callable: one whole time-domain window through
    the ONE-program twin (:func:`~disco_tpu.enhance.fused.
    streaming_clip_fused` — window STFT, masks applied, the scanned
    two-step streaming pipeline and ISTFT inside a single dispatch),
    resolved with exactly the :func:`_resolve_step` discipline.
    Time-domain sessions never group into multi-window scans: the window
    STFT's reflect padding is per-window, so concatenating two windows
    would change the transform — every dispatch is one window at
    ``blocks_per_dispatch=1`` and the RPC amortization comes from the
    window WIDTH (one fenced dispatch per ``block_frames`` STFT frames of
    audio), not from grouping.

    No reference counterpart (module docstring)."""
    return _resolve_step(
        "streaming_clip_fused", "serve_chained_step",
        statics=("update_every", "ref_mic", "mask_type", "policy", "solver",
                 "blocks_per_dispatch", "stft_impl", "precision"),
    )


class Scheduler:
    """Session registry + the per-tick continuous-batching loop body.

    Thread model: ``open_session`` / ``push_block`` / ``request_close`` are
    called from the server's I/O thread; :meth:`tick` runs on the single
    dispatch thread (the ONLY place jax is entered — one chip claim per
    process, per the environment contract).  The registry lock is never
    held across device work.
    """

    def __init__(self, *, max_sessions: int = 16, max_queue_blocks: int = 8,
                 max_blocks_per_tick: int = DEFAULT_MAX_BLOCKS_PER_TICK,
                 blocks_per_super_tick: int = 1,
                 overlap_readback: bool | None = None,
                 allow_chained: bool = True,
                 fault_spec=None, tap=None,
                 dispatch_retries: int = 2,
                 dispatch_retry_base_s: float = 0.05,
                 retry_seed: int = 0,
                 tick_deadline_s: float | None = None,
                 park_ttl_s: float = 60.0,
                 replay_blocks: int = 64,
                 quarantine_ticks: int = 20,
                 max_quarantines: int = 2,
                 shed_retry_after_s: float = 1.0,
                 wait_window_ticks: int = 50,
                 ladder=None, state_dir=None, promote=None, resident=None):
        if max_sessions < 1 or max_queue_blocks < 1 or max_blocks_per_tick < 1:
            raise ValueError("scheduler bounds must be >= 1")
        if blocks_per_super_tick < 1:
            raise ValueError("blocks_per_super_tick must be >= 1")
        if blocks_per_super_tick > max_blocks_per_tick:
            # no group of N could ever form inside the tick budget — the
            # knob would be silently inert (same fail-at-startup rule as
            # the --max-blocks-per-tick plumbing fix in PR 5)
            raise ValueError(
                f"blocks_per_super_tick={blocks_per_super_tick} exceeds "
                f"max_blocks_per_tick={max_blocks_per_tick}: no super-tick "
                "could ever form"
            )
        self.max_sessions = max_sessions
        self.max_queue_blocks = max_queue_blocks
        self.max_blocks_per_tick = max_blocks_per_tick
        #: N: every run of N consecutive full queued blocks of a session is
        #: dispatched as ONE scanned super-tick program
        #: (streaming_tango_scan) — one fenced readback share per N blocks.
        #: The sub-N remainder (and a ragged final block) falls back to the
        #: per-block path, so exactly two programs exist per shape bucket
        #: (per-block + N-scan) and the last partial window never waits for
        #: more input.
        self.blocks_per_super_tick = blocks_per_super_tick
        #: Double-buffered tick state: when on, tick T dispatches its work
        #: FIRST and then reads back tick T-1's batch, so the device computes
        #: super-tick T while the host drains super-tick T-1's readback (the
        #: pipeline.py overlap pattern applied to serving).  Deliveries lag
        #: one tick; an idle tick flushes the buffer.  Default: on exactly
        #: when super-ticks are on.
        self.overlap_readback = (blocks_per_super_tick > 1
                                 if overlap_readback is None else overlap_readback)
        #: admit ``domain="time"`` (chained-lane) sessions?  Each chained
        #: shape bucket compiles its own one-program window; an operator
        #: who wants the bounded STFT-only compile surface turns the lane
        #: off at the door (``disco-serve --no-chained-sessions``).
        self.allow_chained = allow_chained
        self.fault_spec = fault_spec
        #: opt-in flywheel corpus tap (disco_tpu.flywheel.CorpusTap), fed at
        #: the post-readback seam with every delivered block's host arrays
        #: (noisy Y, masks, enhanced yf).  The tap's offer() never blocks
        #: and never raises — overflow drops-and-counts inside the tap —
        #: so serving cannot backpressure or crash on its own telemetry.
        self.tap = tap
        if dispatch_retries < 0 or park_ttl_s <= 0 or replay_blocks < 1:
            raise ValueError("survival knobs out of range (dispatch_retries "
                             ">= 0, park_ttl_s > 0, replay_blocks >= 1)")
        if quarantine_ticks < 1 or max_quarantines < 0 or wait_window_ticks < 1:
            raise ValueError("quarantine/window knobs out of range")
        #: transport-retry budget per dispatch/readback call (retries past
        #: the first attempt; exhausted transport budget = quarantine)
        self.dispatch_retries = dispatch_retries
        self.dispatch_retry_base_s = dispatch_retry_base_s
        #: base seed of the per-dispatch jittered backoff draws (each call
        #: derives seed + dispatch counter — deterministic, desynchronized)
        self.retry_seed = retry_seed
        #: per-tick wall deadline for dispatch+readback (None = watchdog
        #: off); on expiry the tick is marked suspect, the device is fenced
        #: via preflight_probe and the hit feeds the degradation ladder
        self.tick_deadline_s = tick_deadline_s
        #: how long a parked session waits for its client to reattach
        #: before the slot is reclaimed (EVICTED, ``park_expired`` counter)
        self.park_ttl_s = park_ttl_s
        #: per-session replay-buffer depth (bit-exact reattach window)
        self.replay_blocks = replay_blocks
        self.quarantine_ticks = quarantine_ticks
        self.max_quarantines = max_quarantines
        #: reattach back-off hint carried in the shed/park error frame
        self.shed_retry_after_s = shed_retry_after_s
        #: queue-wait samples older than this many ticks age out of the
        #: ladder's p95 window (recovery after load drops)
        self.wait_window_ticks = wait_window_ticks
        #: optional DegradationLadder (serve/ladder.py); None = ladder off
        self.ladder = ladder
        #: park-checkpoint directory (the server's --state-dir); parked
        #: sessions are checkpointed here on the next tick so a reattach
        #: survives even a server death in between
        self.state_dir = state_dir
        #: optional PromotionController (promote/controller.py).  The
        #: controller only ever *requests* generation swaps; this scheduler's
        #: dispatch thread executes them at block boundaries
        #: (:meth:`_apply_generation_swaps`) — the one-generation-per-block
        #: invariant of the promote-check gate.  None = promotion off and
        #: every promote seam in this file is a single attribute check.
        self.promote = promote
        #: per-generation device model cache {gen_id: (model, vars_device)},
        #: dispatch-thread-only.  The flax module instance is shared per
        #: arch (store.model_for_arch), so a new generation reuses the same
        #: jitted programs — only its weights move to the device here.
        self._gen_models: dict = {}
        if promote is not None:
            promote.bind(self)
        #: optional co-resident trainer (flywheel/resident.py).  Stepped at
        #: the END of every tick — after serving work is dispatched and the
        #: ladder has folded this tick's metrics — with the current rung,
        #: so an overloaded tick trains ZERO steps (the ladder-aware
        #: contract).  All of the trainer's jax work happens inside that
        #: call, i.e. on this dispatch thread: the single-chip-claim
        #: contract needs no new jax_ok role.  None = training off and the
        #: seam is one attribute check per tick.
        self.resident = resident
        self.draining = False
        self._lock = threading.Lock()
        self._sessions: dict[str, Session] = {}
        self._parked: dict[str, Session] = {}
        self._session_seq = 0
        self._rotate = 0
        self.ticks_with_work = 0
        #: monotonically increasing tick number (quarantine release + the
        #: ladder's deterministic clock)
        self.tick_no = 0
        self._dispatch_seq = 0
        self._to_checkpoint: list = []
        #: (session, reason, retry_after_s) park notices the server posts
        #: as ``parked`` error frames (shed happens on the dispatch thread,
        #: frames go out on the I/O thread)
        self._park_notices: list = []
        #: (tick_no, wait_ms) samples feeding the ladder's p95 window
        self._wait_samples: list = []
        self._tap_suspended = False
        #: dispatched-but-not-read-back units from the previous tick
        #: (overlap_readback):
        #: [(session, [seq, ...], yf_device, t_dispatch, raw_blocks)] where
        #: raw_blocks keeps the input (seq, Y, mz, mw) host tuples for the
        #: corpus tap (None when no tap — no point pinning the memory)
        self._inflight: list = []

    # -- registry (I/O thread) ----------------------------------------------
    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions.values())

    def get(self, session_id: str) -> Session | None:
        with self._lock:
            return self._sessions.get(session_id)

    def model_session_ids(self) -> list:
        """Ids of the OPEN model-mask sessions — the promotion controller's
        canary-eligible set (any thread).

        No reference counterpart (module docstring)."""
        with self._lock:
            return [s.id for s in self._sessions.values()
                    if s.status == OPEN and s.config.masks == "model"]

    def open_session(self, config, *, session_id: str | None = None,
                     z_mask=None, resume_from=None,
                     priority: bool = False) -> Session:
        """Admit one session (or resume a checkpointed one).

        Parked sessions count toward ``max_sessions`` — a park holds its
        slot for the TTL (so a reattach can never be rejected for
        capacity), and the TTL bounds how long an absent client can do so.

        Raises :class:`AdmissionError` on capacity / draining / config
        problems — the server turns those into clean ``error`` frames.
        """
        if self.draining:
            obs_registry.counter("admission_reject").inc()
            raise AdmissionError("draining", "server is draining; not admitting sessions")
        if not isinstance(config, SessionConfig):
            try:
                config = SessionConfig.from_dict(config)
            except ValueError as e:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError("bad_config", str(e)) from None
        if config.masks == "model" and self.promote is None:
            obs_registry.counter("admission_reject").inc()
            raise AdmissionError(
                "bad_config",
                'masks="model" needs a promotion store; start the server '
                "with --promote-dir",
            )
        if config.domain == "time" and not self.allow_chained:
            obs_registry.counter("admission_reject").inc()
            raise AdmissionError(
                "bad_config",
                'domain="time" (chained-lane) sessions are disabled on this '
                "server (--no-chained-sessions)",
            )

        with self._lock:
            if len(self._sessions) + len(self._parked) >= self.max_sessions:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "capacity",
                    f"server at max_sessions={self.max_sessions}; retry later",
                )
            self._session_seq += 1
            seq = self._session_seq

        if resume_from is not None:
            session = load_session_state(resume_from)
            if session.config != config:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "config_mismatch",
                    f"checkpoint {resume_from} was made with a different "
                    f"session config; resume with the original one",
                )
            if session_id is not None:
                session.id = session_id
            session.priority = bool(priority)
            session.replay = type(session.replay)(maxlen=self.replay_blocks)
        else:
            from disco_tpu.enhance.streaming import initial_stream_state

            sid = session_id or f"s{seq:06d}"
            z_avail = self._session_fault_plan(config, seq, z_mask)
            session = Session(
                sid, config,
                z_avail=z_avail,
                priority=priority,
                replay_blocks=self.replay_blocks,
                state=initial_stream_state(
                    config.n_nodes, config.mics_per_node, config.n_freq,
                    update_every=config.update_every, ref_mic=config.ref_mic,
                ),
            )
        session.open_seq = seq
        if config.masks == "model":
            # every open (fresh or checkpoint-resumed) adopts the store's
            # ACTIVE pointer — generations are deliberately NOT persisted in
            # session checkpoints, so a crash mid-rollout lands every
            # resumed session back on the committed generation (the
            # rollback-on-crash semantics the chaos legs pin)
            try:
                gen = self.promote.active_generation()
            except RuntimeError as e:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError("bad_config", str(e)) from None
            session.set_generation(gen, at_seq=session.blocks_done)
        with self._lock:
            if session.id in self._sessions or session.id in self._parked:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "duplicate", f"session id {session.id!r} already live"
                )
            self._sessions[session.id] = session
        obs_events.record(
            "session", stage="serve", action="open", session=session.id,
            resumed_blocks=session.blocks_done,
            faulted=session.z_avail is not None,
        )
        self._set_gauges()
        return session

    def _session_fault_plan(self, config: SessionConfig, seq: int, z_mask):
        """Per-session z availability: an explicit client mask wins; else a
        server fault spec is expanded per session (seeded off the admission
        sequence number, so every session draws its own deterministic
        realization — ablation runs reproduce exactly)."""
        if z_mask is not None:
            mask = np.asarray(z_mask, np.float32)
            if mask.shape not in ((config.n_nodes,),) and (
                mask.ndim != 2 or mask.shape[0] != config.n_nodes
            ):
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "bad_config",
                    f"z_mask shape {mask.shape} does not match n_nodes={config.n_nodes}",
                )
            return mask
        if self.fault_spec is None or not self.fault_spec.any_fault():
            return None
        import dataclasses

        from disco_tpu.fault.inject import plan_faults

        spec = dataclasses.replace(self.fault_spec, seed=self.fault_spec.seed + seq)
        plan = plan_faults(spec, config.n_nodes, n_blocks=FAULT_PLAN_BLOCKS)
        plan.record(mode="serve")
        if not plan.any_fault():
            return None
        return np.asarray(plan.avail_streaming, np.float32)

    def push_block(self, session: Session, seq: int, Y, mask_z, mask_w,
                   trace=None) -> None:
        """Accept one input block (I/O thread).  Validates shape/order and
        enforces the queue bound (:class:`QueueFull` = backpressure).

        ``trace``: the block frame's causal-trace header (a wire dict with
        ``trace``/``span`` ids — ``obs.trace``), or None for a pre-span
        client.  With tracing enabled, acceptance records the ``enqueue``
        hop and threads the advanced context through the session; with it
        disabled (or for untraced blocks) this costs one attribute check."""
        cfg = session.config
        if session.status not in (OPEN, DRAINING):
            raise QueueFull(f"session {session.id} is {session.status}")
        if seq != session.blocks_in:
            raise QueueFull(
                f"out-of-order block seq {seq} (expected {session.blocks_in}); "
                "blocks must arrive in order"
            )
        Y = np.asarray(Y)
        if not np.issubdtype(Y.dtype, np.number):
            # the wire codec round-trips ANY declared dtype; a non-numeric
            # block must die here as a bad_block, not inside the dispatch
            # thread (where it would read as a server crash)
            raise ValueError(f"block Y dtype {Y.dtype} is not numeric")
        exp = cfg.block_shape
        if Y.shape[:-1] != exp[:-1] or Y.shape[-1] > exp[-1] or Y.shape[-1] < 1:
            raise QueueFull(
                f"block shape {Y.shape} does not fit session shape {exp} "
                "(only the final block may be shorter)"
            )
        if cfg.domain == "time":
            # the chained lane: each block is one float time window whose
            # STFT frame count must stay refresh-aligned (the scan's
            # contract) — reject at the door, not as a dispatch-thread
            # evict the client can't interpret
            if np.iscomplexobj(Y):
                raise QueueFull(
                    f"session {session.id} has domain='time'; blocks must "
                    "be float time windows, not complex STFT frames"
                )
            t_frames = cfg.frames_of(Y.shape[-1])
            if t_frames % cfg.update_every:
                raise QueueFull(
                    f"time window of {Y.shape[-1]} samples has {t_frames} "
                    f"STFT frames — not a multiple of update_every="
                    f"{cfg.update_every} (chunk-exact streaming needs "
                    "refresh-aligned windows)"
                )
        else:
            t_frames = Y.shape[-1]
        if cfg.masks == "model":
            # the model-mask lane: blocks arrive maskless and the dispatch
            # thread fills both masks from the session's current weight
            # generation (promote/lane.py) — a client that sends masks
            # anyway is confused about its own config and dies loudly here
            if mask_z is not None or mask_w is not None:
                raise QueueFull(
                    f'session {session.id} has masks="model"; blocks must '
                    "not carry mask_z/mask_w"
                )
        else:
            for name, m in (("mask_z", mask_z), ("mask_w", mask_w)):
                m = np.asarray(m)  # disco-lint: disable=DL002 -- wire-decoded host arrays on the I/O thread; no device array can reach push_block
                if not np.issubdtype(m.dtype, np.number):
                    raise ValueError(f"{name} dtype {m.dtype} is not numeric")
                if m.shape != (cfg.n_nodes, cfg.n_freq, t_frames):
                    raise QueueFull(f"{name} shape {m.shape} does not match block {Y.shape}")
        if session.queue_depth() >= self.max_queue_blocks:
            raise QueueFull(
                f"session {session.id} input queue at max_queue_blocks="
                f"{self.max_queue_blocks}; wait for enhanced blocks"
            )
        ctx = None
        if obs_trace.enabled() and trace is not None:
            ctx = obs_trace.from_wire(trace)
            ctx = obs_trace.span(
                "enqueue", ctx, session=session.id, seq=int(seq),
                depth=session.queue_depth(),
            )
            obs_trace.tracer().inflight_begin(
                (session.id, int(seq)), ctx, "enqueue",
                session=session.id, seq=int(seq),
            )
        session.push_block(
            seq, Y,
            None if mask_z is None else np.asarray(mask_z),
            None if mask_w is None else np.asarray(mask_w),
            time.time(), trace_ctx=ctx)
        self._set_gauges()

    def request_close(self, session: Session) -> None:
        session.close_requested = True

    def evict(self, session: Session, reason: str) -> None:
        """Drop a session that is not keeping up (unread output backlog,
        exhausted quarantine budget).  The server sends the clean ``error``
        frame; this records the decision and frees the slot."""
        with self._lock:
            self._sessions.pop(session.id, None)
            self._parked.pop(session.id, None)
        session.status = EVICTED
        session.error = reason
        self._drop_traces(session)
        obs_registry.counter("session_evicted").inc()
        obs_events.record("session", stage="serve", action="evict",
                          session=session.id, reason=reason)
        self._set_gauges()

    # -- parking / reattach (I/O + dispatch threads) -------------------------
    def parked_sessions(self) -> list:
        """Snapshot of the parked registry (drain checkpoints these too).

        No reference counterpart (module docstring)."""
        with self._lock:
            return list(self._parked.values())

    def park(self, session: Session, reason: str, *, notice: bool = False,
             retry_after_s: float = 0.0) -> bool:
        """Park a live session instead of evicting it: keep carry + queue +
        replay buffer, hold the admission slot, and wait ``park_ttl_s`` for
        the client to reattach.  ``notice=True`` queues a ``parked`` error
        frame (resume token + back-off hint) for the server to post — the
        shed path, where the connection is still up.  Returns False when
        the session already left the live registry (close/evict race).

        Called from the I/O thread (connection drop, protocol truncation)
        and the dispatch thread (ladder shedding); the checkpoint itself is
        deferred to the next tick, the only place jax may be entered.

        No reference counterpart (module docstring)."""
        with self._lock:
            live = self._sessions.pop(session.id, None)
            if live is None:
                return False
            self._parked[session.id] = session
            session.status = PARKED
            session.parked_at = time.monotonic()
            session.outage_tick = self.tick_no
            self._to_checkpoint.append(session)
            if notice:
                self._park_notices.append((session, reason, retry_after_s))
        obs_registry.counter("sessions_parked_total").inc()
        obs_events.record("session", stage="serve", action="park",
                          session=session.id, reason=reason,
                          blocks_done=session.blocks_done)
        obs_flight.auto_dump("park", reason=f"session {session.id}: {reason}")
        self._set_gauges()
        return True

    def reattach(self, session_id: str, config, have: int | None):
        """Reattach a parked session in place (I/O thread): validate the
        config and the replay coverage, move the session back to the live
        registry, and return ``(session, resume_seq)`` — the output seq the
        server's posting cursor restarts from (the actual frame re-sends
        happen on the dispatch loop, the ONE thread that ever posts
        ``enhanced`` frames, so replay can never race an in-flight
        delivery into a duplicate or a loss).  ``have`` is the next output
        seq the client still needs; ``None`` means a FRESH client resuming
        with just the token (plain ``open(resume=...)``) — it gets resume
        semantics, ``blocks_done``, nothing replayed.  Returns ``None``
        when ``session_id`` is not parked here (the server then falls back
        to the checkpoint-resume path).

        No reference counterpart (module docstring)."""
        with self._lock:
            session = self._parked.get(session_id)
        if session is None:
            return None
        if config is not None and not isinstance(config, SessionConfig):
            try:
                config = SessionConfig.from_dict(config)
            except ValueError as e:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError("bad_config", str(e)) from None
        if config is not None and session.config != config:
            obs_registry.counter("admission_reject").inc()
            raise AdmissionError(
                "config_mismatch",
                f"session {session_id} was parked with a different config; "
                "reattach with the original one",
            )
        if have is None:
            resume_seq = session.blocks_done
        else:
            resume_seq = int(have)
            try:
                session.replay_from(resume_seq)   # coverage validation only
            except SessionStateError as e:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError("resume_gap", str(e)) from None
        with self._lock:
            if self._parked.pop(session_id, None) is None:
                return None   # TTL expiry raced us; treat as not parked
            self._sessions[session_id] = session
            session.status = OPEN
            session.parked_at = None
            session.outage_tick = self.tick_no
        if (self.promote is not None and session.config.masks == "model"
                and session.generation is not None):
            # a rollout can end while a session is parked (its swap request
            # was voided): a reattaching session whose generation is neither
            # ACTIVE nor the live candidate is stale and re-adopts ACTIVE —
            # the same boundary semantics as a checkpoint resume
            active = self.promote.active_generation()
            if session.generation not in (active,
                                          self.promote.current_candidate()):
                session.set_generation(active, at_seq=session.blocks_done)
        obs_registry.counter("session_reattached").inc()
        obs_events.record("session", stage="serve", action="reattach",
                          session=session.id, resume_seq=resume_seq,
                          blocks_done=session.blocks_done)
        self._set_gauges()
        return session, resume_seq

    def drain_park_notices(self) -> list:
        """Take the pending ``parked`` notices (dispatch loop → server,
        which posts the error frames on the I/O thread).

        No reference counterpart (module docstring)."""
        with self._lock:
            notices, self._park_notices = self._park_notices, []
        return notices

    def _expire_parks(self) -> None:
        """Reclaim parked slots whose TTL ran out (dispatch thread)."""
        now = time.monotonic()
        with self._lock:
            expired = [s for s in self._parked.values()
                       if s.parked_at is not None
                       and now - s.parked_at > self.park_ttl_s]
            for s in expired:
                self._parked.pop(s.id, None)
        for s in expired:
            s.status = EVICTED
            s.error = f"parked session expired after {self.park_ttl_s:g}s TTL"
            self._drop_traces(s)
            obs_registry.counter("park_expired").inc()
            obs_events.record("session", stage="serve", action="park_expire",
                              session=s.id, blocks_done=s.blocks_done)
        if expired:
            self._set_gauges()

    def _checkpoint_parked(self) -> None:
        """Checkpoint freshly parked sessions (dispatch thread — the one
        place the device carry may be read back).  An IO failure demotes to
        a ``warning`` event: the in-memory park still works, only the
        crash-survival copy is missing.  A ChaosCrash (BaseException) from
        the mid_write seam still unwinds like a process death."""
        with self._lock:
            batch, self._to_checkpoint = self._to_checkpoint, []
        if self.state_dir is None or not batch:
            return
        from pathlib import Path

        from disco_tpu.serve.session import save_session_state

        state_dir = Path(self.state_dir)
        state_dir.mkdir(parents=True, exist_ok=True)
        for s in batch:
            if s.status != PARKED:
                continue   # reattached (or expired) before we got here
            try:
                save_session_state(
                    state_dir / f"session_{s.id}.state.msgpack", s)
            except Exception as e:
                obs_events.record(
                    "warning", stage="serve",
                    reason=f"park checkpoint failed for {s.id}: "
                           f"{type(e).__name__}: {e}",
                )

    # -- promotion (dispatch thread) -----------------------------------------
    def _apply_generation_swaps(self) -> None:
        """Execute the promotion controller's requested generation swaps —
        HERE, on the dispatch thread, and only for sessions sitting at a
        block boundary (``inflight == 0``): every block a session ever
        dispatches therefore runs under exactly ONE generation, which is
        what makes per-generation replay bit-exact (the promote-check
        oracle).  Sessions not at a boundary are retried next tick;
        sessions that left the live registry are reported void.

        The ``pre_swap`` chaos seam fires here, after the rollout intent is
        durable in the ledger but before any session moved — a crash kills
        the whole server mid-rollout and the restart must resume from the
        ledger with every session on the incumbent (the strongest drill).

        When a state dir is configured, the session is checkpointed through
        the atomic ``save_session_state`` path at the boundary first — the
        park-checkpoint contract of the swap: the on-disk carry a resume
        would adopt was produced entirely under the old generation.

        No reference counterpart (module docstring)."""
        swaps = self.promote.pending_swaps()
        if not swaps:
            return
        from disco_tpu.runs import chaos

        for sid, gen, kind in swaps:
            with self._lock:
                session = self._sessions.get(sid)
            if session is None or session.status not in (OPEN, DRAINING):
                self.promote.note_swap_void(sid)
                continue
            if session.inflight != 0:
                continue   # mid-flight: not at a boundary — next tick
            chaos.tick("pre_swap", session=sid, gen=gen, swap=kind)
            boundary = session.blocks_done
            if self.state_dir is not None:
                from pathlib import Path

                from disco_tpu.serve.session import save_session_state

                state_dir = Path(self.state_dir)
                state_dir.mkdir(parents=True, exist_ok=True)
                try:
                    save_session_state(
                        state_dir / f"session_{sid}.state.msgpack", session)
                except Exception as e:
                    obs_events.record(
                        "warning", stage="serve",
                        reason=f"swap checkpoint failed for {sid}: "
                               f"{type(e).__name__}: {e}",
                    )
            session.set_generation(gen, at_seq=boundary)
            ev_kind, ev_action = {"canary": ("canary", "swap"),
                                  "promote": ("promotion", "adopt"),
                                  "rollback": ("rollback", "swap")}[kind]
            obs_events.record(ev_kind, stage="serve", action=ev_action,
                              session=sid, gen=gen, seq=boundary)
            self.promote.note_swapped(sid, gen, boundary)
        # drop device weights no live or parked session references anymore
        # (a rolled-back candidate must not pin its variables on device)
        refs = {s.generation for s in self.sessions()}
        refs |= {s.generation for s in self.parked_sessions()}
        for g in [g for g in self._gen_models if g not in refs]:
            del self._gen_models[g]

    def _gen_model(self, gen_id: str):
        """(model, device variables) for one generation — cache miss loads
        through the controller (digest-verified) and moves the weights to
        the device once (dispatch thread only).

        No reference counterpart (module docstring)."""
        entry = self._gen_models.get(gen_id)
        if entry is None:
            import jax

            from disco_tpu.utils.transfer import to_device

            model, variables = self.promote.model_for(gen_id)
            variables = jax.tree_util.tree_map(to_device, variables)
            entry = self._gen_models[gen_id] = (model, variables)
        return entry

    def _fill_model_masks(self, session: Session, blocks: list) -> None:
        """Fill a model-mask session's popped blocks IN PLACE with masks
        from its current generation (promote/lane.py) — before grouping, so
        the scan path, the corpus tap and a transport-retry requeue all see
        the same computed masks (a retried block is never recomputed under
        a later generation).

        No reference counterpart (module docstring)."""
        from disco_tpu.promote.lane import block_masks

        model, variables = self._gen_model(session.generation)
        for i, (seq, Y, mz, mw) in enumerate(blocks):
            if mz is not None:
                continue   # already filled (requeued after a retry)
            m = block_masks(Y, model, variables,
                            ref_mic=session.config.ref_mic)
            blocks[i] = (seq, Y, m, m)

    # -- quarantine (dispatch thread) ----------------------------------------
    def _quarantine(self, session: Session, error: BaseException) -> None:
        """Transport budget exhausted for one session: cool it off instead
        of letting it poison every tick with a fresh retry storm.  The
        ``max_quarantines``-th offense evicts."""
        session.quarantine_count += 1
        if session.quarantine_count > self.max_quarantines:
            self.evict(
                session,
                f"transport failures exhausted the quarantine budget "
                f"({self.max_quarantines}): {type(error).__name__}: {error}",
            )
            return
        session.status = QUARANTINED
        session.quarantine_until_tick = self.tick_no + self.quarantine_ticks
        session.outage_tick = self.tick_no
        obs_registry.counter("session_quarantined").inc()
        obs_events.record(
            "session", stage="serve", action="quarantine",
            session=session.id, strike=session.quarantine_count,
            until_tick=session.quarantine_until_tick,
            error=f"{type(error).__name__}: {error}",
        )
        obs_flight.auto_dump(
            "quarantine",
            reason=f"session {session.id} strike {session.quarantine_count}: "
                   f"{type(error).__name__}: {error}",
        )
        self._set_gauges()

    def _release_quarantined(self) -> None:
        """Re-open quarantined sessions whose cool-off elapsed."""
        for s in self.sessions():
            if s.status == QUARANTINED and self.tick_no >= s.quarantine_until_tick:
                s.status = OPEN
                s.outage_tick = self.tick_no
                obs_events.record("session", stage="serve",
                                  action="unquarantine", session=s.id)
                self._set_gauges()

    def _drop_traces(self, session: Session) -> None:
        """Terminal-state trace cleanup: a session that will never deliver
        its pending blocks must not leave ghost entries in the tracer's
        bounded in-flight table (an hours-long traced run would otherwise
        fill MAX_INFLIGHT and stop tracking real blocks).

        No reference counterpart (module docstring)."""
        for seq in session.drain_traces():
            obs_trace.tracer().inflight_end((session.id, seq))

    def _finish(self, session: Session) -> None:
        with self._lock:
            self._sessions.pop(session.id, None)
        session.status = CLOSED
        self._drop_traces(session)
        obs_registry.counter("session_closed").inc()
        obs_events.record("session", stage="serve", action="close",
                          session=session.id, blocks=session.blocks_done)
        self._set_gauges()

    # -- dispatch (scheduler thread) ----------------------------------------
    def tick(self) -> list:
        """One continuous-batching step.

        Returns ``[(session, seq, yf, latency_s), ...]`` host-side
        deliveries (``yf`` numpy complex64), plus finishes sessions whose
        close was requested and whose queues (and in-flight dispatches)
        drained.  Exactly one batched readback per tick that reads work
        back; none on an idle tick.  With super-ticks on
        (``blocks_per_super_tick`` = N > 1), each session's popped blocks
        ride scanned dispatches in groups of N (the sub-N remainder goes
        per-block), and with ``overlap_readback``
        the readback of the previous tick's batch happens *after* this
        tick's dispatches are queued — the device computes super-tick T+1
        while the host reads super-tick T.
        """
        from disco_tpu.runs import chaos
        from disco_tpu.utils.resilience import DispatchDeadline, TRANSPORT_ERRORS

        chaos.tick("serve_tick")
        self.tick_no += 1
        self._release_quarantined()
        self._expire_parks()
        self._checkpoint_parked()
        if self.promote is not None:
            self._apply_generation_swaps()
        sessions = self.sessions()
        if sessions:
            # rotate the starting session each tick: under sustained overload
            # the per-tick block budget runs out, and a fixed registry order
            # would starve the sessions at the tail indefinitely
            k = self._rotate % len(sessions)
            self._rotate += 1
            sessions = sessions[k:] + sessions[:k]
        units: list = []  # (session, [seq, ...], yf_device, t_dispatch, raw)
        keep_raw = self.tap is not None and not self._tap_suspended
        budget = self.max_blocks_per_tick
        # ladder rung >= 1: fall back to the per-block path (the program
        # every shape bucket already has — no new trace)
        n_super = (1 if self.ladder is not None and self.ladder.rung >= 1
                   else self.blocks_per_super_tick)
        n_busy = 0
        t0 = time.perf_counter()
        deadline = (DispatchDeadline(self.tick_deadline_s, label="serve_tick")
                    if self.tick_deadline_s else contextlib.nullcontext())
        with deadline:
            for session in sessions:
                if session.status not in (OPEN, DRAINING) or budget <= 0:
                    continue
                if n_super > 1:
                    # align the pop to a multiple of N: a deeper-than-budget
                    # queue must never shed a sub-N remainder through per-block
                    # dispatches every tick just because max_blocks_per_tick
                    # isn't a multiple of N — blocks left queued join the next
                    # tick's scan group instead.  A sub-N *queue* (stream tail /
                    # starved input) still pops in full below and rides the
                    # per-block fallback.  When the budget remainder is < N
                    # (later sessions of a crowded tick), skip — the per-tick
                    # rotation hands this session a full-width slot next tick.
                    cap = budget // n_super * n_super
                    if cap == 0:
                        continue
                else:
                    cap = budget
                blocks = session.pop_blocks(cap)
                if not blocks:
                    continue
                n_busy += 1
                budget -= len(blocks)
                # progress rides a mutable cell, NOT the return value: when
                # the dispatch raises mid-pop, the blocks dispatched BEFORE
                # the failure are already in `units` with the carry advanced
                # — requeueing them too would re-enhance them through a
                # double-advanced carry (duplicated, wrong deliveries)
                progress = [0]
                try:
                    self._dispatch_blocks(session, blocks, n_super,
                                          units, keep_raw, progress)
                except TRANSPORT_ERRORS as e:
                    # transport budget exhausted even after the per-call
                    # retries: the carry never advanced for the blocks past
                    # `progress`, so they re-queue in order (bit-identical
                    # later retry) and the session cools off in quarantine
                    # instead of retrying into a sick tunnel every tick
                    self._trace_dispatch_failed(session,
                                                blocks[progress[0]:], e)
                    session.requeue_front(blocks[progress[0]:])
                    self._quarantine(session, e)
                except Exception as e:
                    # per-session isolation: one block the device rejects
                    # (validation can't anticipate every jax TypeError) must
                    # not unwind the dispatch thread and kill every other
                    # live session — a NON-transport error is deterministic,
                    # so evict the offender and keep serving.
                    # ChaosCrash is a BaseException and still dies here.
                    self.evict(
                        session, f"dispatch failed: {type(e).__name__}: {e}"
                    )

            if self.overlap_readback:
                # double buffer: read back the PREVIOUS tick's batch while this
                # tick's dispatches compute; an idle tick flushes the buffer
                to_read, self._inflight = self._inflight, units
            else:
                to_read = units
            deliveries = self._readback(to_read) if to_read else []
        deadline_hits = 0
        if isinstance(deadline, DispatchDeadline) and deadline.expired:
            # the tick is suspect: fence the device through the bounded
            # preflight probe BEFORE deciding anything — a wedged attachment
            # must unwind the dispatch thread cleanly (PreflightFailed; the
            # server catches and drains), never hang silently or be killed
            deadline_hits = 1
            self._probe_after_deadline(deadline)
        if to_read:
            obs_registry.histogram("serve_tick_ms").observe(
                (time.perf_counter() - t0) * 1e3
            )
        obs_registry.gauge("batch_occupancy").set(
            n_busy / self.max_sessions if self.max_sessions else 0.0
        )

        for session in sessions:
            if (session.close_requested and session.status in (OPEN, DRAINING)
                    and session.queue_depth() == 0 and session.inflight == 0):
                self._finish(session)
        self._step_ladder(deadline_hits)
        if self.resident is not None and not self.draining:
            # the co-resident trainer's slice rides the tail of the tick:
            # serving work for this tick is fully dispatched and read back,
            # and the ladder has already folded this tick's distress — a
            # rung at/above the trainer's throttle threshold trains nothing
            self.resident.step(
                tick_no=self.tick_no,
                rung=self.ladder.rung if self.ladder is not None else 0)
        self._set_gauges()
        return deliveries

    def _dispatch_blocks(self, session: Session, blocks: list, n_super: int,
                         units: list, keep_raw: bool,
                         progress: list | None = None) -> int:
        """Dispatch one session's popped blocks (scan groups + per-block
        tail — the grouping comments live in :meth:`tick`); every dispatch
        goes through the transport-retry wrapper.  ``progress`` (a 1-cell
        list) is advanced after every successful dispatch so the caller
        knows exactly which blocks to re-queue when this RAISES mid-pop —
        a plain return value would read as zero on the exception path and
        re-enqueue already-dispatched blocks (delivered twice, through a
        double-advanced carry).  Also returns the final count."""
        bf = session.config.block_frames
        if progress is None:
            progress = [0]
        if self.promote is not None and session.config.masks == "model":
            self._fill_model_masks(session, blocks)
        # the chained (time-domain) lane: every block is one whole window
        # through the one-program twin — never scan-grouped (per-window
        # reflect padding, _serve_chained_step docstring) and never tapped
        # (the corpus tap's shard contract is STFT tuples)
        chained = session.config.domain == "time"
        if chained:
            keep_raw = False
        done = 0
        # every run of N consecutive full blocks rides one scanned
        # dispatch; the sub-N remainder (or a group holding the
        # ragged final block — always the stream's last) goes
        # per-block, so a deep queue amortizes at the same 1-fence-
        # per-N rate as an exactly-N one (the scanned program only
        # ever sees N full refresh-aligned blocks).
        for g in range(0, len(blocks), n_super):
            group = blocks[g:g + n_super]
            if (not chained and n_super > 1 and len(group) == n_super
                    and all(b[1].shape[-1] == bf for b in group)):
                yf = self._dispatch_resilient(self._dispatch_scan,
                                              session, group)
                units.append(
                    (session, [b[0] for b in group], yf, time.time(),
                     group if keep_raw else None)
                )
                session.inflight += len(group)
                done += len(group)
                progress[0] = done
                self._trace_dispatch(session, [b[0] for b in group],
                                     len(group))
            else:
                for seq, Y, mz, mw in group:
                    yf = self._dispatch_resilient(
                        self._dispatch_chained if chained else self._dispatch,
                        session, seq, Y, mz, mw)
                    units.append(
                        (session, [seq], yf, time.time(),
                         [(seq, Y, mz, mw)] if keep_raw else None)
                    )
                    session.inflight += 1
                    done += 1
                    progress[0] = done
                    self._trace_dispatch(session, [seq], 1)
        return done

    def _trace_dispatch(self, session: Session, seqs: list, n_group: int) -> None:
        """Record the ``dispatch`` hop for each just-dispatched block and
        advance its stored trace head (dispatch thread).  ``wait_ms`` is
        the enqueue→dispatch queue wait — the waterfall's admission-wait
        attribution.  One attribute check when tracing is off or the
        blocks are untraced.

        No reference counterpart (module docstring)."""
        if not obs_trace.enabled():
            return
        now = time.time()
        for seq in seqs:
            ctx = session.get_trace(seq)
            if ctx is None:
                continue
            t_in = session.enqueued_at.get(seq)
            ctx = obs_trace.span(
                "dispatch", ctx, session=session.id, seq=int(seq),
                tick=self.tick_no, group=n_group,
                wait_ms=(round(max(now - t_in, 0.0) * 1e3, 3)
                         if t_in is not None else None),
            )
            session.set_trace(seq, ctx)
            obs_trace.tracer().inflight_update((session.id, int(seq)),
                                               "dispatch")

    def _trace_dispatch_failed(self, session: Session, blocks: list,
                               error: BaseException) -> None:
        """Record a FAILED ``dispatch`` span for the first undispatched
        block of a transport-exhausted pop (dispatch thread).  The stored
        trace head is NOT advanced — the eventual retry re-chains its own
        ``dispatch`` hop from the same ``enqueue`` parent, so the surviving
        chain stays linear while the flight dump still names the failing
        span (the scope-check fault leg pins this).

        No reference counterpart (module docstring)."""
        if not obs_trace.enabled() or not blocks:
            return
        seq = blocks[0][0]
        ctx = session.get_trace(seq)
        if ctx is None:
            return
        obs_trace.span(
            "dispatch", ctx, session=session.id, seq=int(seq),
            tick=self.tick_no, failed=True,
            error=f"{type(error).__name__}: {error}",
        )

    def _dispatch_resilient(self, fn, session: Session, *args):
        """One dispatch under the transport-retry contract: transient
        ``TRANSPORT_ERRORS`` retry with seeded-jitter backoff (each failed
        attempt is a ``fault`` event, each late success a ``recovery`` —
        utils/resilience.py), deterministic per (retry_seed, dispatch
        counter); any other exception raises straight through to the
        evict path.  The carry only advances on success, so a retried
        attempt is bit-identical to a first try."""
        from disco_tpu.utils.resilience import TRANSPORT_ERRORS, call_with_retries

        self._dispatch_seq += 1
        return call_with_retries(
            fn, session, *args,
            retries=self.dispatch_retries,
            base_delay_s=self.dispatch_retry_base_s,
            max_delay_s=0.5,
            retry_on=TRANSPORT_ERRORS,
            jitter=0.5,
            jitter_seed=self.retry_seed + self._dispatch_seq,
            label="serve_dispatch",
        )

    def _probe_after_deadline(self, deadline) -> None:
        """A tick blew its wall deadline: fence the device via the bounded
        preflight probe.  Success means the device answers again (the
        suspect tick merely finished late — the ladder handles the rest);
        ``PreflightFailed`` propagates and unwinds the dispatch thread
        cleanly (never SIGKILL — parked/checkpointed sessions resume on the
        next server)."""
        from disco_tpu.utils.resilience import preflight_probe

        probe = preflight_probe(deadline_s=max(self.tick_deadline_s, 5.0),
                                retries=1)
        obs_events.record(
            "warning", stage="serve",
            reason=f"tick {self.tick_no} exceeded its "
                   f"{self.tick_deadline_s:g}s dispatch deadline "
                   f"(finished in {deadline.elapsed_s():.3f}s); device "
                   f"probe ok in {probe['dur_s']}s",
        )
        obs_flight.auto_dump(
            "watchdog",
            reason=f"tick {self.tick_no} blew its "
                   f"{self.tick_deadline_s:g}s dispatch deadline",
        )

    def _step_ladder(self, deadline_hits: int) -> None:
        """Feed the degradation ladder this tick's metrics and apply the
        rung's effects (super-tick shrink is read by the next tick; the tap
        gate and shedding apply here)."""
        if self.ladder is None:
            return
        cutoff = self.tick_no - self.wait_window_ticks
        self._wait_samples = [s for s in self._wait_samples if s[0] > cutoff]
        window = [ms for (_t, ms) in self._wait_samples]
        p95 = float(np.percentile(window, 95)) if window else 0.0
        obs_registry.gauge("queue_wait_p95_ms").set(p95)
        rung = self.ladder.observe(queue_wait_p95_ms=p95,
                                   deadline_hits=deadline_hits,
                                   tick=self.tick_no)
        self._tap_suspended = rung >= 2
        if rung >= 3:
            self._shed_one()

    def _shed_one(self) -> None:
        """Shed rung: park the NEWEST non-priority open session (resume
        token + back-off hint ride the ``parked`` error frame), one per
        tick while the rung holds — load sheds gradually and reversibly,
        and every shed client can come back."""
        candidates = [s for s in self.sessions()
                      if s.status == OPEN and not s.priority]
        if not candidates:
            return
        victim = max(candidates, key=lambda s: s.open_seq)
        obs_registry.counter("sessions_shed").inc()
        self.park(victim, "shed: overload (degradation ladder)",
                  notice=True, retry_after_s=self.shed_retry_after_s)

    def _readback(self, units: list) -> list:
        """ONE batched readback over ``units`` and the per-block delivery
        bookkeeping.  A super-tick unit's (K, F, N*block_frames) output is
        split back into its N per-seq blocks host-side (pure slicing — the
        scanned program computed them back to back along the frame axis).

        The ``serve_block_latency_ms`` total is split into its two
        components so super-tick tuning is observable:
        ``serve_queue_wait_ms`` (enqueue → dispatch: admission wait) and
        ``serve_dispatch_ms`` (dispatch → host delivery: device time plus
        the fenced readback share — and, with ``overlap_readback`` on, the
        deliberate one-tick buffering lag; the two components always sum to
        the total, so the delivery cost of the overlap is charged here, not
        hidden).
        """
        from disco_tpu.utils.resilience import TRANSPORT_ERRORS, call_with_retries
        from disco_tpu.utils.transfer import device_get_tree

        n_blocks = sum(len(seqs) for (_, seqs, _, _, _) in units)
        n_sessions = len({s.id for (s, _, _, _, _) in units})
        with obs_events.stage("serve_tick", n_blocks=n_blocks,
                              n_sessions=n_sessions):
            # the batched readback is a tunnel crossing like any other:
            # transient failures retry under the same seeded-jitter budget
            # as dispatch.  An EXHAUSTED budget raises — the carries already
            # advanced on device, so there is no bit-exact way to replay
            # this tick; the server unwinds cleanly and parked/checkpointed
            # sessions resume on a healthy attachment.
            self._dispatch_seq += 1
            t_rb0 = time.perf_counter()
            host = call_with_retries(
                device_get_tree, [yf for (_, _, yf, _, _) in units],
                retries=self.dispatch_retries,
                base_delay_s=self.dispatch_retry_base_s,
                max_delay_s=0.5,
                retry_on=TRANSPORT_ERRORS,
                jitter=0.5,
                jitter_seed=self.retry_seed + self._dispatch_seq,
                label="serve_readback",
            )
        readback_ms = round((time.perf_counter() - t_rb0) * 1e3, 3)
        now = time.time()
        lat_hist = obs_registry.histogram("serve_block_latency_ms")
        wait_hist = obs_registry.histogram("serve_queue_wait_ms")
        disp_hist = obs_registry.histogram("serve_dispatch_ms")
        deliveries = []
        tracing = obs_trace.enabled()
        delivered_ctx: dict = {}
        for (session, seqs, _, t_disp, raw), yf in zip(units, host):
            bf = session.config.block_frames
            for j, seq in enumerate(seqs):
                blk = yf if len(seqs) == 1 else yf[..., j * bf:(j + 1) * bf]
                t_in = session.enqueued_at.pop(seq, None)
                lat_s = (now - t_in) if t_in is not None else 0.0
                lat_hist.observe(lat_s * 1e3)
                if tracing:
                    ctx = session.pop_trace(seq)
                    if ctx is not None:
                        ctx = obs_trace.span(
                            "readback", ctx, session=session.id, seq=int(seq),
                            tick=self.tick_no, readback_ms=readback_ms,
                            n_blocks=n_blocks,
                        )
                        ctx = obs_trace.span(
                            "deliver", ctx, session=session.id, seq=int(seq),
                            latency_ms=round(lat_s * 1e3, 3),
                        )
                        delivered_ctx[(session.id, int(seq))] = ctx
                        obs_trace.tracer().inflight_end((session.id, int(seq)))
                if t_in is not None:
                    wait_ms = max(t_disp - t_in, 0.0) * 1e3
                    wait_hist.observe(wait_ms)
                    if (self.ladder is not None
                            and self.tick_no - session.outage_tick
                            > self.wait_window_ticks):
                        # post-outage backlog flush measures the outage,
                        # not the load: keep it out of the ladder's p95
                        # (session.outage_tick docstring has the rationale);
                        # with no ladder, nothing prunes the window, so
                        # nothing may feed it either
                        self._wait_samples.append((self.tick_no, wait_ms))
                disp_hist.observe(max(now - t_disp, 0.0) * 1e3)
                session.blocks_done = max(session.blocks_done, seq + 1)
                session.inflight = max(session.inflight - 1, 0)
                # the reattach replay buffer: a copy of the delivered block
                # survives the connection it was meant for (super-tick
                # slices are copied so a parked stream never pins the whole
                # N-block readback buffer)
                session.record_delivery(
                    seq, blk if len(seqs) == 1 else np.ascontiguousarray(blk))
                deliveries.append((session, seq, blk, lat_s))
                if self.promote is not None and session.generation is not None:
                    # advances the canary window (and the gate clock) —
                    # attributed to the generation the block RAN under,
                    # which a swap since dispatch cannot rewrite
                    self.promote.note_delivery(session.id, seq,
                                               session.gen_for(seq))
            if self.tap is not None and not self._tap_suspended and raw:
                # THE corpus-tap seam: every delivered block's full training
                # tuple is host-resident right here (inputs were retained at
                # dispatch, yf just crossed in the one batched readback).
                # offer() is non-blocking and exception-free by contract.
                # Super-tick slices are COPIED before spooling: a queued
                # view would pin the whole N-block readback buffer and
                # void the tap queue's memory bound under backlog.
                for j, (seq, Y, mz, mw) in enumerate(raw):
                    blk = (yf if len(seqs) == 1
                           else np.ascontiguousarray(yf[..., j * bf:(j + 1) * bf]))
                    self.tap.offer(session.id, seq, Y, mz, mw, blk,
                                   trace=delivered_ctx.get((session.id,
                                                            int(seq))))
        self.ticks_with_work += 1
        obs_registry.counter("serve_ticks").inc()
        obs_registry.counter("serve_blocks").inc(n_blocks)
        if any(len(seqs) > 1 for (_, seqs, _, _, _) in units):
            obs_registry.counter("serve_super_ticks").inc()
        return deliveries

    def _dispatch(self, session: Session, seq: int, Y, mz, mw):
        """Queue one block's streaming step on device (async — no
        readback).  The call goes through the exact offline entry point
        with the session's carry; only ``out["yf"]`` is fetched later, but
        the whole program (z exchange, hold, both steps) runs as offline."""
        if _DISPATCH_FAULT_INJECTOR is not None:
            _DISPATCH_FAULT_INJECTOR(session.id, [seq])
        import jax

        from disco_tpu.utils.transfer import to_device

        from disco_tpu.enhance.streaming import _float_kw

        cfg = session.config
        u = cfg.update_every
        n_refresh = -(-Y.shape[-1] // u)  # ceil: ragged final block
        step = _serve_step()
        state = jax.tree_util.tree_map(to_device, session.state)
        # lambda_cor / mu are traced floats: jax.jit folds an OMITTED default
        # at trace time but traces a PASSED value — same number, different
        # program, and the warm-up GEVD refreshes amplify the last-ulp
        # difference (see streaming.DEFAULT_LAMBDA_COR).  _float_kw is the
        # one canonical implementation of "pass only when non-default".
        kw = _float_kw(cfg.lambda_cor, cfg.mu)
        out = step(
            to_device(np.ascontiguousarray(Y)),
            to_device(np.ascontiguousarray(mz)),
            to_device(np.ascontiguousarray(mw)),
            update_every=u,
            ref_mic=cfg.ref_mic,
            policy=cfg.policy,
            state=state,
            solver=cfg.solver,
            z_avail=session.block_z_avail(seq, n_refresh),
            **kw,
        )
        session.state = out["state"]
        return out["yf"]

    def _dispatch_chained(self, session: Session, seq: int, y, mz, mw):
        """Queue one time-domain session's window on device (async — no
        readback): the chained lane's counterpart of :meth:`_dispatch`.
        The whole window rides ONE jitted program — window STFT, the masks
        applied, the scanned two-step streaming pipeline and the ISTFT
        (:func:`~disco_tpu.enhance.fused.streaming_clip_fused`) — so only
        the float window crosses in and only the enhanced float window and
        the continuation carry cross out.  The carry is the same streaming
        state pytree as the STFT lane's: a window boundary is a block
        boundary for checkpoints, generation swaps and replay unchanged."""
        if _DISPATCH_FAULT_INJECTOR is not None:
            _DISPATCH_FAULT_INJECTOR(session.id, [seq])
        import jax

        from disco_tpu.utils.transfer import to_device

        from disco_tpu.enhance.streaming import _float_kw

        cfg = session.config
        u = cfg.update_every
        n_refresh = cfg.frames_of(y.shape[-1]) // u
        step = _serve_chained_step()
        state = jax.tree_util.tree_map(to_device, session.state)
        kw = _float_kw(cfg.lambda_cor, cfg.mu)
        out = step(
            to_device(np.ascontiguousarray(y)),
            masks_z=to_device(np.ascontiguousarray(mz)),
            mask_w=to_device(np.ascontiguousarray(mw)),
            update_every=u,
            ref_mic=cfg.ref_mic,
            policy=cfg.policy,
            state=state,
            solver=cfg.solver,
            z_avail=session.block_z_avail(seq, n_refresh),
            **kw,
        )
        session.state = out["state"]
        return out["yf"]

    def _dispatch_scan(self, session: Session, blocks: list):
        """Queue one super-tick on device: N contiguous full blocks through
        the scanned program (async — no readback).  Identical calling
        convention to :meth:`_dispatch` — same carry, same per-refresh-block
        availability columns (the scan slices them back into exactly the
        per-block chunks), same traced-float discipline — so the result is
        bit-identical to N per-block dispatches (the stream-check gate)."""
        if _DISPATCH_FAULT_INJECTOR is not None:
            _DISPATCH_FAULT_INJECTOR(session.id, [b[0] for b in blocks])
        import jax

        from disco_tpu.utils.transfer import to_device

        from disco_tpu.enhance.streaming import _float_kw

        cfg = session.config
        u = cfg.update_every
        Y = np.concatenate([np.ascontiguousarray(b[1]) for b in blocks], axis=-1)
        mz = np.concatenate([np.ascontiguousarray(b[2]) for b in blocks], axis=-1)
        mw = np.concatenate([np.ascontiguousarray(b[3]) for b in blocks], axis=-1)
        n_refresh = Y.shape[-1] // u  # grouped blocks are full: exact
        step = _serve_scan_step()
        state = jax.tree_util.tree_map(to_device, session.state)
        kw = _float_kw(cfg.lambda_cor, cfg.mu)
        out = step(
            to_device(Y),
            to_device(mz),
            to_device(mw),
            update_every=u,
            ref_mic=cfg.ref_mic,
            policy=cfg.policy,
            state=state,
            solver=cfg.solver,
            z_avail=session.block_z_avail(blocks[0][0], n_refresh),
            blocks_per_dispatch=len(blocks),
            **kw,
        )
        session.state = out["state"]
        return out["yf"]

    def pending_blocks(self) -> int:
        """Blocks not yet delivered: queued plus dispatched-in-flight (the
        drain gate must wait for the overlap buffer to flush before the
        final checkpoint, so checkpoints land on delivered-block
        boundaries)."""
        return sum(s.queue_depth() + s.inflight for s in self.sessions())

    def _set_gauges(self) -> None:
        with self._lock:
            n = len(self._sessions)
            n_parked = len(self._parked)
            n_quar = sum(1 for s in self._sessions.values()
                         if s.status == QUARANTINED)
            depth = sum(s.queue_depth() for s in self._sessions.values())
        obs_registry.gauge("sessions_active").set(n)
        obs_registry.gauge("sessions_parked").set(n_parked)
        obs_registry.gauge("sessions_quarantined").set(n_quar)
        obs_registry.gauge("queue_depth").set(depth)

    # -- drain / checkpoint (dispatch thread) --------------------------------
    def checkpoint_sessions(self, state_dir) -> dict:
        """Checkpoint every live session's carry under ``state_dir`` —
        states fetched in ONE batched readback, files placed atomically
        (:func:`~disco_tpu.serve.session.save_session_state`).  Returns
        {session_id: path}."""
        from pathlib import Path

        from disco_tpu.serve.session import fetch_state_host, save_session_state

        state_dir = Path(state_dir)
        sessions = [s for s in self.sessions()
                    if s.status in (OPEN, DRAINING, QUARANTINED)]
        # parked sessions checkpoint too: their client may reattach to the
        # NEXT server via the resume token, which only works if the carry
        # survives this one
        sessions += self.parked_sessions()
        if not sessions:
            return {}
        host_states = fetch_state_host({s.id: s.state for s in sessions})
        paths = {}
        for s in sessions:
            path = state_dir / f"session_{s.id}.state.msgpack"
            save_session_state(path, s, state_host=host_states[s.id])
            paths[s.id] = str(path)
        return paths

    def start_drain(self) -> None:
        """Stop admitting; mark every live session draining (their queued
        blocks still run to completion on subsequent ticks)."""
        self.draining = True
        for s in self.sessions():
            if s.status == OPEN:
                s.status = DRAINING
