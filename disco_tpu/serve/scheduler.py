"""Continuous-batching scheduler: many concurrent sessions, one device.

Each tick gathers the ready blocks across all live sessions and runs them
as one device batch in the sense that matters on this hardware: every
block's :func:`~disco_tpu.enhance.streaming.streaming_tango` step is
dispatched *asynchronously* (no readback between sessions — dispatches
queue on device), and the tick's outputs cross the host boundary in ONE
complex-safe :func:`~disco_tpu.utils.transfer.device_get_tree` — the same
discipline as the corpus engine (``enhance/pipeline.fetch_chunk_host``),
where the fixed ~80 ms RPC per fenced readback, not per-op compute, is the
cost model (CLAUDE.md).  ``device_get_batches`` therefore advances exactly
once per tick-with-work, which is what ``make serve-check`` asserts.

Why not one vmapped megabatch: a vmapped program compiles *different
fusions* than the offline per-clip program, and the warm-up GEVD refreshes
run on near-degenerate covariances where a one-ulp covariance difference
flips the ``ffill`` hold guard and diverges the whole stream — measured at
~1.0 relative error on synthetic CPU streams.  Per-session dispatch through
the **same jitted callable the offline path uses** makes serve output
bit-identical to ``streaming_tango`` by construction (the serve-check
parity gate), while the *shape bucket* — sessions sharing a
:class:`~disco_tpu.serve.session.SessionConfig` — still bounds compiles to
one program per bucket via the jit cache (``counted_jit`` makes any drift
visible as ``jit_trace`` events).  Off-CPU the step re-jits the same
function with the carry donated (``donate_argnames=("state",)``): identical
HLO math, buffers reused in place — the corpus engine's donation rule.

Admission control is first-class: a bounded session count
(``admission_reject`` counter), a bounded per-session input queue
(backpressure errors instead of unbounded host memory), and slow-client
eviction hooks (``session_evicted``).  Telemetry: ``sessions_active`` /
``queue_depth`` / ``batch_occupancy`` gauges and the
``serve_block_latency_ms`` histogram, all rendered by ``disco-obs report``.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.serve.session import (
    CLOSED,
    DRAINING,
    EVICTED,
    OPEN,
    Session,
    SessionConfig,
    load_session_state,
)

#: Default bound on blocks enhanced per tick across all sessions — keeps
#: one tick's device queue (and its single readback payload) bounded, so a
#: bursty client cannot starve the others for a whole tick.
DEFAULT_MAX_BLOCKS_PER_TICK = 64

#: Refresh-block horizon of a per-session fault plan drawn from a server
#: ``--fault-spec`` (``plan_faults`` needs a concrete width; blocks past
#: the horizon are treated as delivered).
FAULT_PLAN_BLOCKS = 4096


class AdmissionError(RuntimeError):
    """Session rejected at the door (capacity, draining, bad config)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class QueueFull(RuntimeError):
    """Per-session input queue bound hit — backpressure, not a crash."""


_STEP = None
_STEP_LOCK = threading.Lock()


def _serve_step():
    """The per-block step callable.

    CPU: literally ``enhance.streaming.streaming_tango`` — the offline
    jitted wrapper itself, so serve and offline share one compiled program
    per shape bucket and parity is true by construction.  Off-CPU: a
    ``counted_jit`` of the same underlying function with the continuation
    carry donated (aliasing metadata only — the HLO math is unchanged).
    """
    global _STEP
    if _STEP is None:
        with _STEP_LOCK:
            if _STEP is None:
                import jax

                from disco_tpu.enhance import streaming
                from disco_tpu.obs.accounting import counted_jit

                if jax.default_backend() == "cpu":
                    _STEP = streaming.streaming_tango
                else:
                    _STEP = counted_jit(
                        streaming.streaming_tango.__wrapped__,
                        label="serve_step",
                        static_argnames=(
                            "update_every", "ref_mic", "with_diagnostics",
                            "policy", "solver",
                        ),
                        donate_argnames=("state",),
                    )
    return _STEP


class Scheduler:
    """Session registry + the per-tick continuous-batching loop body.

    Thread model: ``open_session`` / ``push_block`` / ``request_close`` are
    called from the server's I/O thread; :meth:`tick` runs on the single
    dispatch thread (the ONLY place jax is entered — one chip claim per
    process, per the environment contract).  The registry lock is never
    held across device work.
    """

    def __init__(self, *, max_sessions: int = 16, max_queue_blocks: int = 8,
                 max_blocks_per_tick: int = DEFAULT_MAX_BLOCKS_PER_TICK,
                 fault_spec=None):
        if max_sessions < 1 or max_queue_blocks < 1 or max_blocks_per_tick < 1:
            raise ValueError("scheduler bounds must be >= 1")
        self.max_sessions = max_sessions
        self.max_queue_blocks = max_queue_blocks
        self.max_blocks_per_tick = max_blocks_per_tick
        self.fault_spec = fault_spec
        self.draining = False
        self._lock = threading.Lock()
        self._sessions: dict[str, Session] = {}
        self._session_seq = 0
        self._rotate = 0
        self.ticks_with_work = 0

    # -- registry (I/O thread) ----------------------------------------------
    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions.values())

    def get(self, session_id: str) -> Session | None:
        with self._lock:
            return self._sessions.get(session_id)

    def open_session(self, config, *, session_id: str | None = None,
                     z_mask=None, resume_from=None) -> Session:
        """Admit one session (or resume a checkpointed one).

        Raises :class:`AdmissionError` on capacity / draining / config
        problems — the server turns those into clean ``error`` frames.
        """
        if self.draining:
            obs_registry.counter("admission_reject").inc()
            raise AdmissionError("draining", "server is draining; not admitting sessions")
        if not isinstance(config, SessionConfig):
            try:
                config = SessionConfig.from_dict(config)
            except ValueError as e:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError("bad_config", str(e)) from None

        with self._lock:
            if len(self._sessions) >= self.max_sessions:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "capacity",
                    f"server at max_sessions={self.max_sessions}; retry later",
                )
            self._session_seq += 1
            seq = self._session_seq

        if resume_from is not None:
            session = load_session_state(resume_from)
            if session.config != config:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "config_mismatch",
                    f"checkpoint {resume_from} was made with a different "
                    f"session config; resume with the original one",
                )
            if session_id is not None:
                session.id = session_id
        else:
            from disco_tpu.enhance.streaming import initial_stream_state

            sid = session_id or f"s{seq:06d}"
            z_avail = self._session_fault_plan(config, seq, z_mask)
            session = Session(
                sid, config,
                z_avail=z_avail,
                state=initial_stream_state(
                    config.n_nodes, config.mics_per_node, config.n_freq,
                    update_every=config.update_every, ref_mic=config.ref_mic,
                ),
            )
        with self._lock:
            if session.id in self._sessions:
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "duplicate", f"session id {session.id!r} already live"
                )
            self._sessions[session.id] = session
        obs_events.record(
            "session", stage="serve", action="open", session=session.id,
            resumed_blocks=session.blocks_done,
            faulted=session.z_avail is not None,
        )
        self._set_gauges()
        return session

    def _session_fault_plan(self, config: SessionConfig, seq: int, z_mask):
        """Per-session z availability: an explicit client mask wins; else a
        server fault spec is expanded per session (seeded off the admission
        sequence number, so every session draws its own deterministic
        realization — ablation runs reproduce exactly)."""
        if z_mask is not None:
            mask = np.asarray(z_mask, np.float32)
            if mask.shape not in ((config.n_nodes,),) and (
                mask.ndim != 2 or mask.shape[0] != config.n_nodes
            ):
                obs_registry.counter("admission_reject").inc()
                raise AdmissionError(
                    "bad_config",
                    f"z_mask shape {mask.shape} does not match n_nodes={config.n_nodes}",
                )
            return mask
        if self.fault_spec is None or not self.fault_spec.any_fault():
            return None
        import dataclasses

        from disco_tpu.fault.inject import plan_faults

        spec = dataclasses.replace(self.fault_spec, seed=self.fault_spec.seed + seq)
        plan = plan_faults(spec, config.n_nodes, n_blocks=FAULT_PLAN_BLOCKS)
        plan.record(mode="serve")
        if not plan.any_fault():
            return None
        return np.asarray(plan.avail_streaming, np.float32)

    def push_block(self, session: Session, seq: int, Y, mask_z, mask_w) -> None:
        """Accept one input block (I/O thread).  Validates shape/order and
        enforces the queue bound (:class:`QueueFull` = backpressure)."""
        cfg = session.config
        if session.status not in (OPEN, DRAINING):
            raise QueueFull(f"session {session.id} is {session.status}")
        if seq != session.blocks_in:
            raise QueueFull(
                f"out-of-order block seq {seq} (expected {session.blocks_in}); "
                "blocks must arrive in order"
            )
        Y = np.asarray(Y)
        if not np.issubdtype(Y.dtype, np.number):
            # the wire codec round-trips ANY declared dtype; a non-numeric
            # block must die here as a bad_block, not inside the dispatch
            # thread (where it would read as a server crash)
            raise ValueError(f"block Y dtype {Y.dtype} is not numeric")
        exp = cfg.block_shape
        if Y.shape[:-1] != exp[:-1] or Y.shape[-1] > exp[-1] or Y.shape[-1] < 1:
            raise QueueFull(
                f"block shape {Y.shape} does not fit session shape {exp} "
                "(only the final block may be shorter)"
            )
        for name, m in (("mask_z", mask_z), ("mask_w", mask_w)):
            m = np.asarray(m)
            if not np.issubdtype(m.dtype, np.number):
                raise ValueError(f"{name} dtype {m.dtype} is not numeric")
            if m.shape != (cfg.n_nodes, cfg.n_freq, Y.shape[-1]):
                raise QueueFull(f"{name} shape {m.shape} does not match block {Y.shape}")
        if session.queue_depth() >= self.max_queue_blocks:
            raise QueueFull(
                f"session {session.id} input queue at max_queue_blocks="
                f"{self.max_queue_blocks}; wait for enhanced blocks"
            )
        session.push_block(seq, Y, np.asarray(mask_z), np.asarray(mask_w), time.time())
        self._set_gauges()

    def request_close(self, session: Session) -> None:
        session.close_requested = True

    def evict(self, session: Session, reason: str) -> None:
        """Drop a session that is not keeping up (unread output backlog,
        dead connection).  The server sends the clean ``error`` frame; this
        records the decision and frees the slot."""
        with self._lock:
            self._sessions.pop(session.id, None)
        session.status = EVICTED
        session.error = reason
        obs_registry.counter("session_evicted").inc()
        obs_events.record("session", stage="serve", action="evict",
                          session=session.id, reason=reason)
        self._set_gauges()

    def _finish(self, session: Session) -> None:
        with self._lock:
            self._sessions.pop(session.id, None)
        session.status = CLOSED
        obs_events.record("session", stage="serve", action="close",
                          session=session.id, blocks=session.blocks_done)
        self._set_gauges()

    # -- dispatch (scheduler thread) ----------------------------------------
    def tick(self) -> list:
        """One continuous-batching step.

        Returns ``[(session, seq, yf, latency_s), ...]`` host-side
        deliveries (``yf`` numpy complex64), plus finishes sessions whose
        close was requested and whose queues drained.  Exactly one batched
        readback when any block ran; none on an idle tick.
        """
        from disco_tpu.runs import chaos

        chaos.tick("serve_tick")
        sessions = self.sessions()
        if sessions:
            # rotate the starting session each tick: under sustained overload
            # the per-tick block budget runs out, and a fixed registry order
            # would starve the sessions at the tail indefinitely
            k = self._rotate % len(sessions)
            self._rotate += 1
            sessions = sessions[k:] + sessions[:k]
        work: list = []        # (session, seq, yf_device)
        budget = self.max_blocks_per_tick
        n_busy = 0
        t0 = time.perf_counter()
        for session in sessions:
            if session.status not in (OPEN, DRAINING) or budget <= 0:
                continue
            blocks = session.pop_blocks(budget)
            if not blocks:
                continue
            n_busy += 1
            budget -= len(blocks)
            for seq, Y, mz, mw in blocks:
                try:
                    work.append(
                        (session, seq, self._dispatch(session, seq, Y, mz, mw))
                    )
                except Exception as e:
                    # per-session isolation: one block the device rejects
                    # (validation can't anticipate every jax TypeError) must
                    # not unwind the dispatch thread and kill every other
                    # live session — evict the offender and keep serving.
                    # ChaosCrash is a BaseException and still dies here.
                    self.evict(
                        session, f"dispatch failed: {type(e).__name__}: {e}"
                    )
                    break

        deliveries = []
        if work:
            from disco_tpu.utils.transfer import device_get_tree

            with obs_events.stage("serve_tick", n_blocks=len(work), n_sessions=n_busy):
                host = device_get_tree([yf for (_, _, yf) in work])
            now = time.time()
            lat_hist = obs_registry.histogram("serve_block_latency_ms")
            for (session, seq, _), yf in zip(work, host):
                t_in = session.enqueued_at.pop(seq, None)
                lat_s = (now - t_in) if t_in is not None else 0.0
                lat_hist.observe(lat_s * 1e3)
                session.blocks_done = max(session.blocks_done, seq + 1)
                deliveries.append((session, seq, yf, lat_s))
            self.ticks_with_work += 1
            obs_registry.counter("serve_ticks").inc()
            obs_registry.counter("serve_blocks").inc(len(work))
            obs_registry.histogram("serve_tick_ms").observe(
                (time.perf_counter() - t0) * 1e3
            )
        obs_registry.gauge("batch_occupancy").set(
            n_busy / self.max_sessions if self.max_sessions else 0.0
        )

        for session in sessions:
            if (session.close_requested and session.status in (OPEN, DRAINING)
                    and session.queue_depth() == 0):
                self._finish(session)
        self._set_gauges()
        return deliveries

    def _dispatch(self, session: Session, seq: int, Y, mz, mw):
        """Queue one block's streaming step on device (async — no
        readback).  The call goes through the exact offline entry point
        with the session's carry; only ``out["yf"]`` is fetched later, but
        the whole program (z exchange, hold, both steps) runs as offline."""
        import jax

        from disco_tpu.utils.transfer import to_device

        from disco_tpu.enhance.streaming import DEFAULT_LAMBDA_COR, DEFAULT_MU

        cfg = session.config
        u = cfg.update_every
        n_refresh = -(-Y.shape[-1] // u)  # ceil: ragged final block
        step = _serve_step()
        state = jax.tree_util.tree_map(to_device, session.state)
        # lambda_cor / mu are traced floats: jax.jit folds an OMITTED default
        # at trace time but traces a PASSED value — same number, different
        # program, and the warm-up GEVD refreshes amplify the last-ulp
        # difference (see streaming.DEFAULT_LAMBDA_COR).  Mirror the
        # canonical offline call: pass them only when non-default.
        kw = {}
        if cfg.lambda_cor != DEFAULT_LAMBDA_COR:
            kw["lambda_cor"] = cfg.lambda_cor
        if cfg.mu != DEFAULT_MU:
            kw["mu"] = cfg.mu
        out = step(
            to_device(np.ascontiguousarray(Y)),
            to_device(np.ascontiguousarray(mz)),
            to_device(np.ascontiguousarray(mw)),
            update_every=u,
            ref_mic=cfg.ref_mic,
            policy=cfg.policy,
            state=state,
            solver=cfg.solver,
            z_avail=session.block_z_avail(seq, n_refresh),
            **kw,
        )
        session.state = out["state"]
        return out["yf"]

    def pending_blocks(self) -> int:
        return sum(s.queue_depth() for s in self.sessions())

    def _set_gauges(self) -> None:
        with self._lock:
            n = len(self._sessions)
            depth = sum(s.queue_depth() for s in self._sessions.values())
        obs_registry.gauge("sessions_active").set(n)
        obs_registry.gauge("queue_depth").set(depth)

    # -- drain / checkpoint (dispatch thread) --------------------------------
    def checkpoint_sessions(self, state_dir) -> dict:
        """Checkpoint every live session's carry under ``state_dir`` —
        states fetched in ONE batched readback, files placed atomically
        (:func:`~disco_tpu.serve.session.save_session_state`).  Returns
        {session_id: path}."""
        from pathlib import Path

        from disco_tpu.serve.session import fetch_state_host, save_session_state

        state_dir = Path(state_dir)
        sessions = [s for s in self.sessions() if s.status in (OPEN, DRAINING)]
        if not sessions:
            return {}
        host_states = fetch_state_host({s.id: s.state for s in sessions})
        paths = {}
        for s in sessions:
            path = state_dir / f"session_{s.id}.state.msgpack"
            save_session_state(path, s, state_host=host_states[s.id])
            paths[s.id] = str(path)
        return paths

    def start_drain(self) -> None:
        """Stop admitting; mark every live session draining (their queued
        blocks still run to completion on subsequent ticks)."""
        self.draining = True
        for s in self.sessions():
            if s.status == OPEN:
                s.status = DRAINING
