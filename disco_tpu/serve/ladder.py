"""The degradation ladder: a deterministic overload/distress controller.

Between "serve normally" and "reject at the door" the server previously had
nothing: sustained admission past capacity just grew queue waits until
clients timed out, and a distressed device (dispatch-deadline hits) kept
being fed full-width super-ticks.  The ladder gives overload a graded
answer — a small pure controller stepped once per scheduler tick from two
inputs (recent queue-wait p95 and the tick's dispatch-deadline hits), fully
deterministic given that metric trace:

* **rung 0** ``full``      — normal serving, nothing shed.
* **rung 1** ``per_block`` — super-ticks shrink to the per-block path
  (``blocks_per_super_tick`` → 1): smallest dispatch units, lowest
  per-block admission wait, and the program every shape bucket already has
  compiled (no new trace, no disco-trace budget change).
* **rung 2** ``no_tap``    — the flywheel corpus tap is disabled: the
  training spool is strictly best-effort telemetry and is the first whole
  subsystem to go.
* **rung 3** ``shed``      — newest non-priority sessions are parked with a
  resume token (one per tick while the rung holds): the client backs off
  and reattaches when load drops, instead of every session timing out.

Steps UP happen immediately when a tick's metrics breach the high
thresholds (overload must be answered now); steps DOWN require
``recover_ticks`` consecutive calm ticks (hysteresis — no rung flapping).
Every transition is first-class telemetry: a ``degraded`` obs event on the
way up, a ``recovery`` event on the way down, and the ``ladder_rung``
gauge, all rendered by ``disco-obs report``.

The controller itself never touches jax, sessions or sockets: it returns
the target rung and the scheduler applies the effects (the same
observe/apply split as :mod:`disco_tpu.runs.chaos`).

No reference counterpart: the reference pipeline is strictly offline and
cannot be overloaded (SURVEY.md §2).
"""
from __future__ import annotations

from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry

#: Rung names, index == rung number (rendered in events and the docs).
RUNGS = ("full", "per_block", "no_tap", "shed")


class DegradationLadder:
    """Deterministic rung controller (module docstring has the rung map).

    Args:
      p95_high_ms / p95_low_ms: queue-wait p95 thresholds — a tick with
        p95 above ``high`` steps up; only ticks with p95 below ``low``
        count toward recovery (the gap is the hysteresis band).
      deadline_hits_high: dispatch-deadline hits in one tick that step up
        regardless of queue waits (device distress, not load).
      recover_ticks: consecutive calm ticks required per step DOWN.
      max_rung: highest rung this ladder may reach (the serve-check
        overload drill caps at 2 so no parity client is ever shed).

    No reference counterpart (module docstring).
    """

    def __init__(self, *, p95_high_ms: float = 500.0, p95_low_ms: float = 100.0,
                 deadline_hits_high: int = 1, recover_ticks: int = 25,
                 max_rung: int = 3):
        if not 0 < p95_low_ms <= p95_high_ms:
            raise ValueError(
                f"need 0 < p95_low_ms <= p95_high_ms, got "
                f"{p95_low_ms}/{p95_high_ms}"
            )
        if not 0 <= max_rung < len(RUNGS):
            raise ValueError(f"max_rung must be in [0, {len(RUNGS) - 1}], got {max_rung}")
        if recover_ticks < 1 or deadline_hits_high < 1:
            raise ValueError("recover_ticks and deadline_hits_high must be >= 1")
        self.p95_high_ms = p95_high_ms
        self.p95_low_ms = p95_low_ms
        self.deadline_hits_high = deadline_hits_high
        self.recover_ticks = recover_ticks
        self.max_rung = max_rung
        self.rung = 0
        self._calm = 0
        #: (tick, from_rung, to_rung, reason) transition history (the soak
        #: gate asserts recovery; bounded by construction — each entry is a
        #: real transition)
        self.transitions: list = []

    def observe(self, *, queue_wait_p95_ms: float, deadline_hits: int,
                tick: int) -> int:
        """One controller step: fold this tick's metrics, return the rung.

        Pure given its inputs — same metric trace, same rung trace (the
        determinism the serve-check overload drill pins).

        No reference counterpart (module docstring)."""
        hot = (queue_wait_p95_ms > self.p95_high_ms
               or deadline_hits >= self.deadline_hits_high)
        calm = queue_wait_p95_ms < self.p95_low_ms and deadline_hits == 0
        if hot and self.rung < self.max_rung:
            self._calm = 0
            self._step(tick, self.rung + 1,
                       f"queue_wait_p95_ms={queue_wait_p95_ms:.1f} "
                       f"deadline_hits={deadline_hits}")
        elif hot:
            self._calm = 0
        elif calm and self.rung > 0:
            self._calm += 1
            if self._calm >= self.recover_ticks:
                self._calm = 0
                self._step(tick, self.rung - 1,
                           f"calm for {self.recover_ticks} ticks "
                           f"(p95={queue_wait_p95_ms:.1f}ms)")
        else:
            self._calm = 0
        return self.rung

    def _step(self, tick: int, to_rung: int, reason: str) -> None:
        frm, self.rung = self.rung, to_rung
        self.transitions.append((tick, frm, to_rung, reason))
        obs_registry.gauge("ladder_rung").set(to_rung)
        kind = "degraded" if to_rung > frm else "recovery"
        obs_events.record(
            kind, stage="serve", controller="ladder", tick=tick,
            from_rung=frm, rung=to_rung,
            from_mode=RUNGS[frm], mode=RUNGS[to_rung], reason=reason,
        )
        if to_rung > frm:
            obs_registry.counter("ladder_degrades").inc()
            from disco_tpu.obs import flight as obs_flight

            # a step-up is distress: dump the flight ring so the post-
            # mortem has the ticks/spans that led here (no-op unless armed)
            obs_flight.auto_dump(
                "ladder_step_up",
                reason=f"rung {frm}->{to_rung} ({RUNGS[to_rung]}): {reason}",
            )
        else:
            obs_registry.counter("ladder_recoveries").inc()
