"""disco_tpu.serve — online enhancement: continuous batching of concurrent
streaming sessions on one device.

Everything before this package is offline: clips in, artifacts out.  The
streaming TANGO pipeline (``enhance/streaming.py``) already processes audio
in ``update_every``-frame blocks with an explicit continuation carry —
exactly the per-session state an online service needs (DANSE's adaptive
block-update design; Bertrand & Moonen 2010, Furnon et al. 2021).  This
package is the subsystem that turns "one clip, one process" into "many
concurrent sessions, one device":

* :mod:`~disco_tpu.serve.protocol`  — length-prefixed msgpack frames over a
  unix/TCP socket; numpy-only (clients never import jax).
* :mod:`~disco_tpu.serve.session`   — per-stream state: config, streaming
  carry, fault availability, queues; checkpoint/resume via atomic msgpack.
* :mod:`~disco_tpu.serve.scheduler` — the continuous-batching tick: ready
  blocks across sessions dispatched async through the SAME jitted program
  as offline (bit-exact parity), ONE batched readback per tick.
* :mod:`~disco_tpu.serve.server`    — asyncio I/O + one dispatch thread
  (the single chip-claiming thread), graceful drain, chaos seams.
* :mod:`~disco_tpu.serve.client`    — blocking numpy client.
* :mod:`~disco_tpu.serve.check`     — the ``make serve-check`` gate.

No reference counterpart: the reference repo has no online story at all
(SURVEY.md §2); the ROADMAP north star — "serves heavy traffic" — starts
here.
"""
from disco_tpu.serve.client import ServeClient, ServeError
from disco_tpu.serve.ladder import RUNGS, DegradationLadder
from disco_tpu.serve.scheduler import (
    AdmissionError,
    QueueFull,
    Scheduler,
    set_dispatch_fault_injector,
)
from disco_tpu.serve.server import EnhanceServer
from disco_tpu.serve.status import (
    DEFAULT_SLO,
    STATUS_SECTIONS,
    evaluate_slo,
    fetch_status,
    status_payload,
    status_section,
)
from disco_tpu.serve.session import (
    Session,
    SessionConfig,
    SessionStateError,
    load_session_state,
    probe_session_state,
    save_session_state,
)

__all__ = [
    "AdmissionError",
    "DEFAULT_SLO",
    "DegradationLadder",
    "EnhanceServer",
    "QueueFull",
    "RUNGS",
    "STATUS_SECTIONS",
    "Scheduler",
    "ServeClient",
    "ServeError",
    "Session",
    "SessionConfig",
    "SessionStateError",
    "evaluate_slo",
    "fetch_status",
    "load_session_state",
    "probe_session_state",
    "save_session_state",
    "set_dispatch_fault_injector",
    "status_payload",
    "status_section",
]
