"""Live serve introspection: the ``status`` frame payload and SLO verdicts.

Until this module, the only way to inspect a live ``disco-serve`` process
was to SIGINT it and read the drain summary — unacceptable for a server
meant to hold sessions open for hours.  The ``status`` protocol frame
(:mod:`disco_tpu.serve.protocol`) is the read-only answer: any client (no
open session required) receives one ``status_ok`` frame built by
:func:`status_payload` — session states, scheduler tick/drain state,
degradation-ladder rung, the full counters/gauges registry snapshot,
latency-histogram percentiles and the causal tracer's in-flight spans.

The payload is organized into the closed section set
:data:`STATUS_SECTIONS`; readers go through :func:`status_section`, whose
call-site string literals disco-lint rule DL014 checks against the
registry (the same source-parsed, never-imported pattern as the obs event
kinds) — a typo'd section name is a lint failure, not a silent ``None``.

:func:`evaluate_slo` turns one payload into a verdict over declared SLO
targets (serve p95, queue-wait p95, tap drop rate, session evict rate) —
the ``disco-obs slo`` command and its nonzero exit on violation.  The
``make scope-check`` gate additionally pins payload/registry agreement:
the counters section must equal ``obs.REGISTRY.snapshot()["counters"]``.

Everything here is host-only reads under the owning locks — building a
status payload never enters jax, so the I/O thread can serve it while the
dispatch thread owns the chip claim (environment contract).

No reference counterpart: the reference has no serving layer and nothing
long-lived to introspect (SURVEY.md §2, §5.1).
"""
from __future__ import annotations

from disco_tpu.obs import trace as obs_trace
from disco_tpu.obs.metrics import REGISTRY as obs_registry

#: The closed set of status-payload sections (disco-lint DL014 checks
#: ``status_section(payload, "<name>")`` literals against this registry).
STATUS_SECTIONS = (
    "sessions",    # per-session states: id/status/blocks/queue/inflight
    "scheduler",   # tick number, draining flag, capacity knobs
    "ladder",      # degradation-ladder rung + mode (None when ladder off)
    "counters",    # the full counters registry (MUST match REGISTRY.snapshot)
    "gauges",      # the full gauges registry
    "latency",     # serve histogram summaries (p50/p95/p99 ...)
    "inflight",    # the causal tracer's in-flight span table
)

#: Latency histograms surfaced in the ``latency`` section.
_LATENCY_HISTOGRAMS = ("serve_block_latency_ms", "serve_queue_wait_ms",
                       "serve_dispatch_ms", "serve_tick_ms")

#: Default SLO targets (``disco-obs slo`` flags override each).  Chosen for
#: the loopback CPU gate sizes; production declares its own.
DEFAULT_SLO = {
    "serve_p95_ms": 1000.0,       # delivered-block latency p95
    "queue_wait_p95_ms": 500.0,   # enqueue→dispatch wait p95
    "max_drop_rate": 0.01,        # tap drops / tap offers
    "max_evict_rate": 0.05,       # evictions / finished sessions
}


def status_payload(scheduler, *, ladder=None, tracer=None) -> dict:
    """Build the ``status_ok`` payload from a live scheduler (I/O thread;
    host-only reads, never jax).  ``ladder``/``tracer`` default to the
    scheduler's ladder and the process-global tracer.

    No reference counterpart (module docstring).
    """
    ladder = ladder if ladder is not None else scheduler.ladder
    tracer = tracer if tracer is not None else obs_trace.tracer()
    promote = getattr(scheduler, "promote", None)
    sessions = []
    for s in scheduler.sessions() + scheduler.parked_sessions():
        entry = {
            "id": s.id,
            "status": s.status,
            "blocks_in": s.blocks_in,
            "blocks_done": s.blocks_done,
            "queue_depth": s.queue_depth(),
            "inflight": s.inflight,
            "priority": bool(s.priority),
            "quarantine_count": s.quarantine_count,
        }
        if getattr(s, "generation", None) is not None:
            # generation keys exist only on promotion-enabled servers —
            # a promote-less payload stays byte-identical to PR 16
            entry["generation"] = s.generation
        sessions.append(entry)
    snap = obs_registry.snapshot()
    sched_section = {
        "tick_no": scheduler.tick_no,
        "ticks_with_work": scheduler.ticks_with_work,
        "draining": scheduler.draining,
        "max_sessions": scheduler.max_sessions,
        "max_blocks_per_tick": scheduler.max_blocks_per_tick,
        "blocks_per_super_tick": scheduler.blocks_per_super_tick,
        "pending_blocks": scheduler.pending_blocks(),
    }
    if promote is not None:
        sched_section["active_generation"] = promote.store.active()
    return {
        "sessions": sessions,
        "scheduler": sched_section,
        "ladder": (None if ladder is None else {
            "rung": ladder.rung,
            "mode": _rung_name(ladder.rung),
            "transitions": len(ladder.transitions),
        }),
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "latency": {name: snap["histograms"][name]
                    for name in _LATENCY_HISTOGRAMS
                    if name in snap["histograms"]},
        "inflight": (tracer.inflight_snapshot() if tracer.enabled
                     else {"count": 0, "oldest_s": None, "spans": [],
                           "tracing": False}),
    }


def _rung_name(rung: int) -> str:
    from disco_tpu.serve.ladder import RUNGS

    return RUNGS[rung] if 0 <= rung < len(RUNGS) else f"rung{rung}"


def status_section(payload: dict, name: str):
    """One section of a status payload (the DL014-checked accessor: the
    section literal must come from :data:`STATUS_SECTIONS`).  Raises
    :class:`KeyError` on an unknown section — a reader asking for a
    section this server never built must fail loudly, not render blanks.

    No reference counterpart (module docstring).
    """
    if name not in STATUS_SECTIONS:
        raise KeyError(
            f"unknown status section {name!r} (registered: {STATUS_SECTIONS})"
        )
    return payload[name]


def fetch_status(address, timeout_s: float = 10.0) -> dict:
    """Dial a serve server, send one ``status`` frame, return the
    ``status_ok`` payload (numpy+stdlib only — the ``disco-obs top``
    transport; never claims the chip).

    ``address``: ``(host, port)`` tuple or unix-socket path.

    No reference counterpart (module docstring).
    """
    import socket

    from disco_tpu.serve import protocol

    family = (socket.AF_UNIX if isinstance(address, (str, bytes))
              else socket.AF_INET)
    target = address if isinstance(address, (str, bytes)) else tuple(address)
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.settimeout(timeout_s)
    try:
        sock.connect(target)
        protocol.send_frame(sock, {"type": "status"})
        frame = protocol.recv_frame(sock)
    finally:
        sock.close()
    if frame is None or frame.get("type") != "status_ok":
        raise RuntimeError(
            f"status request got {frame.get('type') if frame else 'EOF'!r}, "
            "expected status_ok"
        )
    return frame


def evaluate_slo(payload: dict, targets: dict | None = None) -> dict:
    """Judge one status payload against declared SLO targets.

    Returns ``{"verdict": "OK"|"VIOLATED", "checks": [...]}`` where each
    check carries ``name``/``value``/``target``/``ok`` — an unmeasured
    value (no traffic yet) passes with ``value: None`` rather than
    flagging an idle server.  Rates: ``drop_rate`` is tap drops over tap
    offers; ``evict_rate`` is evictions over finished sessions (evicted +
    closed) — both 0 when the denominator is 0.

    No reference counterpart (module docstring).
    """
    targets = {**DEFAULT_SLO, **(targets or {})}
    counters = status_section(payload, "counters")
    latency = status_section(payload, "latency")
    checks = []

    def check(name, value, target, lower_is_better=True):
        ok = True if value is None else (
            value <= target if lower_is_better else value >= target)
        checks.append({"name": name, "value": value, "target": target,
                       "ok": ok})

    lat = latency.get("serve_block_latency_ms") or {}
    check("serve_p95_ms", lat.get("p95"), targets["serve_p95_ms"])
    wait = latency.get("serve_queue_wait_ms") or {}
    check("queue_wait_p95_ms", wait.get("p95"), targets["queue_wait_p95_ms"])

    offered = counters.get("tap_blocks", 0) + counters.get("tap_dropped", 0)
    drop_rate = counters.get("tap_dropped", 0) / offered if offered else 0.0
    check("drop_rate", round(drop_rate, 6), targets["max_drop_rate"])

    finished = counters.get("session_evicted", 0) + counters.get("session_closed", 0)
    evict_rate = (counters.get("session_evicted", 0) / finished
                  if finished else 0.0)
    check("evict_rate", round(evict_rate, 6), targets["max_evict_rate"])

    return {
        "verdict": "OK" if all(c["ok"] for c in checks) else "VIOLATED",
        "checks": checks,
    }
