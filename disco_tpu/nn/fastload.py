"""ctypes bindings for the native threaded corpus loader.

The C++ library (``disco_tpu/native/fastloader.cpp``) replaces the
single-threaded ``np.load`` + ``np.abs`` loop of the reference's
DiscoDataset.load_data (datasets.py:71-87) with a thread pool that parses
.npy headers and writes magnitudes straight into one preallocated float32
buffer.  Built on demand with g++ (cached next to the source); everything
degrades gracefully to the NumPy path when no compiler is available.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "fastloader.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libfastloader.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded shared library, building it on first use; None if
    unavailable (no compiler / unsupported platform)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Rebuild when the source is newer; a prebuilt .so without the
        # source (installed package) is used as-is.
        have_src = os.path.exists(_SRC)
        stale = (
            not os.path.exists(_LIB)
            or (have_src and os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        )
        if stale and (not have_src or not _build()):
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.fast_load_abs.restype = ctypes.c_int
        lib.fast_load_abs.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_long),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native fastloader library is built and loadable."""
    return get_lib() is not None


def load_abs_batch(paths, n_freq: int, max_frames: int, skip_cols: int = 0, out: np.ndarray | None = None, n_threads: int | None = None):
    """Load |·| of many (n_freq, T<=max_frames) .npy files (complex64 or
    float32) into one (n, n_freq, max_frames) float32 array, zero-padded,
    in parallel.  Returns (array, n_frames per file).

    ``skip_cols`` leading frames of every file are dropped first (the
    reference's first-second silence drop, datasets.py:81).

    Raises RuntimeError naming the offending file on any parse/read error —
    identical failure semantics to the numpy fallback path.
    """
    lib = get_lib()
    paths = [os.fspath(p) for p in paths]
    n = len(paths)
    if out is None:
        out = np.empty((n, n_freq, max_frames), np.float32)
    assert out.shape == (n, n_freq, max_frames) and out.dtype == np.float32
    assert out.flags["C_CONTIGUOUS"]

    if lib is None:  # numpy fallback
        frames = np.zeros(n, np.int64)
        for i, p in enumerate(paths):
            a = np.abs(np.load(p))[:, skip_cols:]
            if a.shape[0] != n_freq:
                raise RuntimeError(f"fastload: {p}: expected {n_freq} rows, got {a.shape[0]}")
            t = min(a.shape[1], max_frames)
            out[i, :, :t] = a[:, :t]
            out[i, :, t:] = 0.0
            frames[i] = t
        return out, frames

    if n_threads is None:
        n_threads = min(32, os.cpu_count() or 4)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    frames = np.zeros(n + 1, np.int64)
    rc = lib.fast_load_abs(
        c_paths,
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.shape[1] * out.shape[2],
        n_freq,
        max_frames,
        skip_cols,
        n_threads,
        frames.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
    )
    if rc != 0:
        bad = int(frames[n])
        raise RuntimeError(f"fastload: failed reading {paths[bad]!r} (unsupported dtype/shape or IO error)")
    return out, frames[:n]
