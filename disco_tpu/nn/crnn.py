"""The CRNN mask-estimation network (reference dnn/models/crnn.py:9-108).

CNN2d feature extractor → reshape keeping the time axis → GRU → FF(sigmoid),
predicting a per-frame mask over ``n_freq`` bins.  The canonical DISCO
instantiation (reference dnn/utils.py:143-152, tango.py:127-132) is

    input (n_ch, 21, 257) → conv filters (32, 64, 64), 3×3, stride 1,
    freq-only pooling (1, 4), conv padding (0, 1) → GRU(256) → FF(257,
    sigmoid)

which yields conv output frames 15 for input window 21 — the frame-
alignment bookkeeping lives in :func:`loss_frame_bounds` / the model's
:meth:`CRNN.loss_frames` (crnn.py:65-87, dnn/utils.py:189-209).

Inputs follow the reference's (batch, channels, time, freq) convention —
3-D inputs get a singleton channel axis (crnn.py:56-57) — and are
transposed once to TPU-friendly NHWC internally.
"""
from __future__ import annotations

import math
from typing import Any, Sequence

import jax.numpy as jnp
import optax
from flax import linen as nn

from disco_tpu.nn.bricks import CNN2d, FF, RNN, _HashableFields, cnn_output_dim


def loss_frame_bounds(win_len: int, part) -> tuple[int, int]:
    """(first, last) frame selecting which of ``win_len`` frames enter the
    loss: 'all' | 'mid' | 'last' | an explicit index
    (reference dnn/utils.py:189-209)."""
    if part == "all":
        return 0, win_len
    if part == "mid":
        first = int(math.ceil(win_len) / 2)
        return first, first + 1
    if part == "last":
        return win_len - 1, win_len
    if isinstance(part, int):
        return part, part + 1
    raise ValueError(f"Unknown output_frames value {part!r}; use 'all', 'mid', 'last' or an int")


class CRNN(_HashableFields, nn.Module):
    """CRNN mask estimator (reference crnn.py:9-87)."""

    input_shape: Sequence[int]  # (n_ch, win_len, n_freq)
    cnn_filters: Sequence[int] = (32, 64, 64)
    conv_kernels: Any = 3
    conv_strides: Any = 1
    pool_kernels: Any = ((1, 4), (1, 4), (1, 4))
    pool_strides: Any = None
    conv_padding: Any = ((0, 1), (0, 1), (0, 1))
    pool_types: Any = "max"
    rnn_units: Sequence[int] = (256,)
    rnn_cell: str = "gru"
    rnn_dropouts: Any = 0.0
    rnn_bi: Any = False
    ff_units: Any = (257,)
    ff_activation: Any = "sigmoid"

    def conv_output_hw(self) -> tuple[int, int]:
        """Analytic (time, freq) shape after the conv stack
        (reference crnn.py:50)."""
        return cnn_output_dim(
            (self.input_shape[1], self.input_shape[2]),
            self.conv_kernels,
            self.conv_strides,
            self.pool_kernels,
            self.pool_strides,
            conv_padding=self.conv_padding,
            n_layers=len(self.cnn_filters),
        )

    def loss_frames(self, output_frames) -> tuple[tuple[int, int], tuple[int, int]]:
        """((ff_in, lf_in), (ff_out, lf_out)): which input frames line up
        with which output frames, accounting for the frames the VALID convs
        crop (reference crnn.py:65-87)."""
        win_in = self.input_shape[1]
        win_out = self.conv_output_hw()[0]
        if output_frames == "last":
            new_len = (win_in + win_out) // 2
            ff_in, lf_in = new_len - 1, new_len
        elif output_frames == "mid":
            ff_in = int(math.ceil(win_in) / 2)
            lf_in = ff_in + 1
        elif output_frames == "all":
            ff_in = (win_in - win_out) // 2
            lf_in = (win_in + win_out) // 2
        else:
            raise ValueError(f"Unknown output_frames value {output_frames!r}")
        return (ff_in, lf_in), loss_frame_bounds(win_out, output_frames)

    @nn.compact
    def __call__(self, x, train: bool = False, stream: bool = False):
        """Windowed mode (default): ``x`` is (B, C, win_len, F) sliding
        windows (3-D gets a singleton channel, reference crnn.py:56-57).

        Stream mode (``stream=True``, inference only): ``x`` is
        (B, C, F, Tp) FULL padded magnitude streams.  The conv stack has no
        time padding (VALID, pad (0, 1) is freq-only), so its output over
        the full stream is exactly the concatenation of the per-window conv
        outputs — convs run ONCE per stream instead of once per window
        (a 21x saving), and only the GRU/FF — whose state resets per window
        by the reference's semantics — run per gathered window.  Returns
        (B, T, win_out, n_freq) per-window outputs, T = Tp - win_len + 1.
        """
        if not stream and x.ndim == 3:
            x = x[:, None]  # (B, T, F) → (B, 1, T, F)
        if stream:
            x = jnp.transpose(x, (0, 3, 2, 1))  # (B, C, F, Tp) → (B, Tp, F, C)
        else:
            x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW → NHWC, once
        x = CNN2d(
            features=tuple(self.cnn_filters),
            conv_kernels=self.conv_kernels,
            conv_strides=self.conv_strides,
            pool_kernels=self.pool_kernels,
            pool_strides=self.pool_strides,
            conv_padding=self.conv_padding,
            pool_types=self.pool_types,
        )(x, train=train)
        b, t, f, c = x.shape
        if stream:
            win_out = self.conv_output_hw()[0]
            n_win = t - win_out + 1
            idx = jnp.arange(n_win)[:, None] + jnp.arange(win_out)[None, :]
            x = x[:, idx]  # (B, n_win, win_out, F', c)
            x = x.reshape(b * n_win, win_out, f * c)
        else:
            # keep time, merge (freq, channels) into features (crnn.py:59)
            x = x.reshape(b, t, f * c)
        x = RNN(
            features=tuple(self.rnn_units),
            cell_type=self.rnn_cell,
            dropouts=self.rnn_dropouts,
            bidirectional=self.rnn_bi,
        )(x, train=train)
        x = FF(features=self.ff_units, activations=self.ff_activation)(x)
        if stream:
            return x.reshape(b, n_win, win_out, -1)
        return x


def build_crnn(
    n_ch: int = 1,
    win_len: int = 21,
    n_freq: int = 257,
    learning_rate: float = 1e-3,
    clip_grad_norm: float | None = None,
    rnn_dropouts: Any = 0.5,
    **overrides,
):
    """(model, optax tx) in the canonical DISCO configuration — conv
    (32, 64, 64) 3×3 / pool (1, 4) / GRU 256 / FF 257 sigmoid, RMSprop
    lr 1e-3 without grad clipping (reference crnn.py:90-108,
    dnn/utils.py:143-152).  Note the reference's rnn_dropouts=0.5 is a
    no-op for the single-layer GRU (last-layer dropout is forced to 0) —
    preserved here.
    """
    model = CRNN(input_shape=(n_ch, win_len, n_freq), rnn_dropouts=rnn_dropouts, **overrides)
    tx = optax.rmsprop(learning_rate, decay=0.99, eps=1e-8)
    if clip_grad_norm:
        tx = optax.chain(optax.clip_by_global_norm(clip_grad_norm), tx)
    return model, tx


class RNNMask(_HashableFields, nn.Module):
    """2-D RNN mask estimator — the reference's 'rnn' architecture path
    (freq-stacked inputs, ``stack_axis=1`` in datasets.py:120-151 and the
    2-D branch of speech_enhancement/utils.py prepare_data:100-120): a
    recurrent stack straight over (B, T, n_ch*n_freq) windows, no convs, so
    every input frame maps to an output frame (no conv cropping)."""

    input_shape: Sequence[int]  # (win_len, n_ch * n_freq)
    rnn_units: Sequence[int] = (256, 256)
    rnn_cell: str = "gru"
    rnn_dropouts: Any = 0.0
    rnn_bi: Any = False
    ff_units: Any = (257,)
    ff_activation: Any = "sigmoid"

    def conv_output_hw(self) -> tuple[int, int]:
        """No conv cropping: output frames == input frames (for the shared
        frames_lost bookkeeping of enhance/inference.py)."""
        return self.input_shape[0], self.input_shape[1]

    def loss_frames(self, output_frames) -> tuple[tuple[int, int], tuple[int, int]]:
        win = self.input_shape[0]
        return (loss_frame_bounds(win, output_frames), loss_frame_bounds(win, output_frames))

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 4:  # (B, C, T, F) → freq-stack the channels
            b, c, t, f = x.shape
            x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b, t, c * f)
        x = RNN(
            features=tuple(self.rnn_units),
            cell_type=self.rnn_cell,
            dropouts=self.rnn_dropouts,
            bidirectional=self.rnn_bi,
        )(x, train=train)
        return FF(features=self.ff_units, activations=self.ff_activation)(x)


def build_rnn(
    n_ch: int = 1,
    win_len: int = 21,
    n_freq: int = 257,
    learning_rate: float = 1e-3,
    clip_grad_norm: float | None = None,
    **overrides,
):
    """(model, optax tx) for the 2-D RNN architecture — the 'rnn' branch the
    reference selects with archi != 'crnn' (train.py:73-74 stack_axis=1,
    utils.py 2-D tensors)."""
    overrides.setdefault("ff_units", (n_freq,))
    model = RNNMask(input_shape=(win_len, n_ch * n_freq), **overrides)
    tx = optax.rmsprop(learning_rate, decay=0.99, eps=1e-8)
    if clip_grad_norm:
        tx = optax.chain(optax.clip_by_global_norm(clip_grad_norm), tx)
    return model, tx
