"""Composable NN bricks — the TPU-native counterpart of the reference's
generic torch modules (reference dnn/models/nn_structures.py:39-245).

Same three building blocks (FF / RNN / CNN2d) with the same knobs, written
as Flax linen modules so the whole model jits into one XLA program:

* ``FF`` — linear stack with per-layer activations fetched by name
  (nn_structures.py:39-76).
* ``RNN`` — stacked RNN/LSTM/GRU cells with per-layer dropout and optional
  bidirectionality; hidden state handled by ``flax.linen.RNN`` scan
  (nn_structures.py:80-158).  ``lax.scan`` under the hood — no Python loop
  over time frames.
* ``CNN2d`` — Conv + BatchNorm + pool per layer (nn_structures.py:162-217),
  plus the analytic output-shape computation ``cnn_output_dim``
  (nn_structures.py:219-245) as a pure function.

Layout note: torch is NCHW; TPU conv wants NHWC.  These bricks take
``(batch, time, freq, channels)`` and treat time as H, frequency as W, so
XLA can tile the convs onto the MXU without layout transposes.
"""
from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

_ACTIVATIONS = {
    "sigmoid": jax.nn.sigmoid,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "elu": jax.nn.elu,
    "softplus": jax.nn.softplus,
    "identity": lambda x: x,
    "linear": lambda x: x,
    None: lambda x: x,
}


def activation_by_name(name):
    """Fetch an activation by (torch-style, lowercase) name — the counterpart
    of ``getattr(torch, activation)`` at nn_structures.py:75."""
    if callable(name):
        return name
    key = name.lower() if isinstance(name, str) else name
    if key in _ACTIVATIONS:
        return _ACTIVATIONS[key]
    fn = getattr(jax.nn, key, None)
    if fn is None:
        raise ValueError(f"Unknown activation {name!r}")
    return fn


def broadcast_arg(arg, n: int) -> list:
    """Scalar → n-list; pair-tuple → repeated n times; list (or tuple of
    per-layer tuples) → as-is.  Reference ``multiply_argument_to_list``
    (nn_structures.py:14-35), extended so flax-friendly tuple-of-tuples
    defaults read as per-layer lists."""
    if isinstance(arg, list):
        if len(arg) == 1:
            return arg * n
        assert len(arg) == n, f"expected 1 or {n} values, got {len(arg)}"
        return arg
    if isinstance(arg, tuple):
        if len(arg) == n and all(e is None or isinstance(e, (tuple, list)) for e in arg):
            return list(arg)  # explicit per-layer spec written as a tuple
        return [arg] * n  # a (h, w) pair, repeated per layer
    return [arg] * n


def spec_per_layer(arg, n: int) -> list:
    """Per-layer structural spec (kernels/strides/pools): sequences are
    indexed per layer as-is (the reference stores these unexpanded,
    nn_structures.py:188-191); scalars broadcast."""
    if arg is None or not isinstance(arg, (tuple, list)):
        return [arg] * n
    assert len(arg) == n, f"expected {n} per-layer values, got {len(arg)}"
    return list(arg)


def _pair(v) -> tuple:
    """int → (int, int); tuples/lists pass through."""
    if v is None:
        return v
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v, v)


def _freeze(v):
    """Recursively lists → tuples so module fields stay hashable (flax
    modules must hash to be jit statics / lru_cache keys)."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(e) for e in v)
    return v


class _HashableFields:
    """Mixin: convert list-typed dataclass fields to tuples at init."""

    def __post_init__(self):
        for f in self.__dataclass_fields__:
            v = getattr(self, f)
            if isinstance(v, list):
                object.__setattr__(self, f, _freeze(v))
        super().__post_init__()


class FF(_HashableFields, nn.Module):
    """Feed-forward stack: Dense layers with named activations
    (nn_structures.py:39-76)."""

    features: Sequence[int]
    activations: Any = "sigmoid"

    @nn.compact
    def __call__(self, x):
        feats = self.features if isinstance(self.features, (tuple, list)) else (self.features,)
        acts = broadcast_arg(
            list(self.activations) if isinstance(self.activations, (tuple, list)) else self.activations,
            len(feats),
        )
        for units, act in zip(feats, acts):
            x = activation_by_name(act)(nn.Dense(units)(x))
        return x


_CELLS = {"rnn": nn.SimpleCell, "lstm": nn.OptimizedLSTMCell, "gru": nn.GRUCell}


class RNN(_HashableFields, nn.Module):
    """Stacked recurrent layers over the time axis (batch, time, features),
    with per-layer dropout (forced to 0 on the last layer, matching
    nn_structures.py:122-126) and optional per-layer bidirectionality.
    """

    features: Sequence[int]
    cell_type: str = "gru"
    dropouts: Any = 0.0
    bidirectional: Any = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = len(self.features)
        drops = broadcast_arg(
            list(self.dropouts) if isinstance(self.dropouts, (tuple, list)) else self.dropouts, n
        )
        drops = list(drops)
        drops[-1] = 0.0  # no dropout after the last layer (nn_structures.py:126)
        bidis = broadcast_arg(self.bidirectional, n)
        cell_cls = _CELLS[self.cell_type.lower()]
        for units, drop, bidi in zip(self.features, drops, bidis):
            fwd = nn.RNN(cell_cls(features=units))
            if bidi:
                bwd = nn.RNN(cell_cls(features=units), reverse=True, keep_order=True)
                x = jnp.concatenate([fwd(x), bwd(x)], axis=-1)
            else:
                x = fwd(x)
            if drop:
                x = nn.Dropout(rate=float(drop), deterministic=not train)(x)
        return x


class CNN2d(_HashableFields, nn.Module):
    """Conv2d → BatchNorm → pool stack over (batch, time, freq, channels)
    (nn_structures.py:162-217).  Integer paddings follow torch semantics:
    explicit zero-pad of (pad_t, pad_f) on both sides, VALID conv/pool.
    ``pool_strides`` entries of None default to the pool kernel (torch
    MaxPool2d behavior)."""

    features: Sequence[int]
    conv_kernels: Any = 3
    conv_strides: Any = 1
    pool_kernels: Any = None
    pool_strides: Any = None
    conv_padding: Any = 0
    pool_types: Any = "max"
    conv_bias: Any = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = len(self.features)
        kernels = [_pair(k) for k in spec_per_layer(self.conv_kernels, n)]
        strides = [_pair(s) for s in spec_per_layer(self.conv_strides, n)]
        pads = [_pair(p) for p in broadcast_arg(self.conv_padding, n)]
        pools = [_pair(p) for p in spec_per_layer(self.pool_kernels, n)]
        pstrides = [_pair(s) for s in spec_per_layer(self.pool_strides, n)]
        ptypes = broadcast_arg(self.pool_types, n)
        biases = broadcast_arg(self.conv_bias, n)

        for i in range(n):
            x = nn.Conv(
                self.features[i],
                kernel_size=kernels[i],
                strides=strides[i],
                padding=[(pads[i][0],) * 2, (pads[i][1],) * 2],
                use_bias=biases[i],
            )(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
            if pools[i] is not None:
                window = pools[i]
                stride = pstrides[i] if pstrides[i] is not None else window
                pool = nn.max_pool if str(ptypes[i]).lower().startswith("max") else nn.avg_pool
                x = pool(x, window_shape=window, strides=stride, padding="VALID")
        return x


def cnn_output_dim(
    input_hw,
    conv_kernels,
    conv_strides,
    pool_kernels,
    pool_strides,
    conv_padding=0,
    n_layers: int | None = None,
) -> tuple[int, int]:
    """Analytic (time, freq) output shape of the conv stack — the pure-
    function equivalent of ``CNN2d.get_output_dim`` (nn_structures.py:219-245,
    torch Conv2d/MaxPool2d floor formulas)."""
    if n_layers is None:
        n_layers = len(conv_kernels) if isinstance(conv_kernels, (list, tuple)) else 1
    kernels = [_pair(k) for k in spec_per_layer(conv_kernels, n_layers)]
    strides = [_pair(s) for s in spec_per_layer(conv_strides, n_layers)]
    pads = [_pair(p) for p in broadcast_arg(conv_padding, n_layers)]
    pools = [_pair(p) for p in spec_per_layer(pool_kernels, n_layers)]
    pstrides = [_pair(s) for s in spec_per_layer(pool_strides, n_layers)]

    h, w = input_hw
    for i in range(n_layers):
        # None conv stride means stride 1 (the flax nn.Conv default CNN2d
        # actually runs with); pool stride None means stride = pool kernel.
        cs = (1, 1) if strides[i] is None else strides[i]
        h = math.floor((h + 2 * pads[i][0] - (kernels[i][0] - 1) - 1) / cs[0] + 1)
        w = math.floor((w + 2 * pads[i][1] - (kernels[i][1] - 1) - 1) / cs[1] + 1)
        if pools[i] is not None:
            ps = pools[i] if pstrides[i] is None else pstrides[i]
            h = math.floor((h - (pools[i][0] - 1) - 1) / ps[0] + 1)
            w = math.floor((w - (pools[i][1] - 1) - 1) / ps[1] + 1)
    return int(h), int(w)
