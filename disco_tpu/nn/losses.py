"""Training losses (reference dnn/engine/losses.py).

``reconstruction_loss`` is the reference's masked-MSE: the squared mask
error *weighted by the input magnitude STFT*, so loud TF bins dominate
(losses.py:15-25).  NaN-robust via a mean that ignores NaNs
(losses.py:4-12).
"""
import jax.numpy as jnp


def nanmean(v):
    """Mean ignoring NaNs (reference losses.py:4-12)."""
    mask = ~jnp.isnan(v)
    return jnp.where(mask, v, 0.0).sum() / mask.sum()


def reconstruction_loss(y_true, y_pred, y_in):
    """MSE of the predicted mask applied on the input STFT:
    ``nanmean(((y_pred - y_true) * y_in)**2)`` (reference losses.py:15-25)."""
    return nanmean(((y_pred - y_true) * y_in) ** 2)
