"""Host-side training data pipeline (reference dnn/data/datasets.py,
dnn/data/lists_to_load.py, dnn/utils.py:74-140).

The reference feeds a torch DataLoader from RAM-resident magnitude STFTs;
here the same windowing semantics produce numpy batches that are fed to the
jitted train step (host → device, one transfer per batch).  Semantics kept
1:1 (datasets.py:40-222):

* items are (segment, start-frame) windows of ``win_len`` frames with hop
  ``win_hop`` and a random sub-hop jitter per draw (datasets.py:105-118);
* each item picks a random *local node*; the input stacks the local node's
  reference-channel magnitude STFT with the other nodes' compressed z
  signals, local node rolled last (datasets.py:120-151);
* labels are the saved ideal-mask frames of the local node
  (datasets.py:153-162);
* the first second (silence prepended at generation) is dropped
  (datasets.py:73,81);
* ``stack_axis`` 0 = single-channel, 1 = stack z's on the frequency axis
  (2-D nets), 2 = stack on a channel axis (3-D CRNN) (datasets.py:60-66).

``RandomDataset`` is the corpus-free fake for smoke tests
(datasets.py:13-36).  ``DiscoPartialDataset`` keeps only the z's in RAM and
reads reference channels / masks lazily per item (datasets.py:165-221).
"""
from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from disco_tpu.io.atomic import atomic_write
from disco_tpu.io.layout import DatasetLayout

TRAIN_DUR = 11  # seconds (datasets.py:6)
FS = 16000  # Hz (datasets.py:7)


class RandomDataset:
    """Random-tensor fake dataset for plumbing smoke tests
    (reference datasets.py:13-36)."""

    def __init__(self, input_shape, output_shape, length=1000, rng=None):
        self.input_shape = input_shape
        self.output_shape = output_shape
        self.length = length
        self.rng = rng or np.random.default_rng()

    def __len__(self):
        return self.length

    def __getitem__(self, index):
        x = self.rng.random(self.input_shape).astype("float32")
        y = self.rng.random(self.output_shape).astype("float32")
        return x, y


class DiscoDataset:
    """Windowed magnitude-STFT dataset, everything RAM-resident
    (reference datasets.py:40-162)."""

    n_nodes = 4

    def __init__(
        self,
        lists_to_load,
        stack_axis=0,
        z_nodes=None,
        fft_len=512,
        fft_hop=256,
        win_len=21,
        win_hop=8,
        rng=None,
    ):
        self.n_fft = fft_len
        self.n_hop = fft_hop
        self.n_freq = fft_len // 2 + 1
        self.win_len = win_len
        self.win_hop = win_hop
        self.segs_to_load = [list(l) for l in lists_to_load]
        self.n_ch = len(self.segs_to_load) - 1
        assert stack_axis in (0, 1, 2), "stack_axis: 0 (SC), 1 (freq-stacked MC) or 2 (channel-stacked MC)"
        self.stack_axis = stack_axis
        self.z_nodes = min(stack_axis, 1) * (self.n_nodes - 1) if z_nodes is None else z_nodes
        self.rng = rng or np.random.default_rng()

        self.data, self.first_seq_frame, self.win_per_seg, self.n_frames = self.load_data()
        self.n_cum = np.cumsum([0] + list(self.win_per_seg))

    # -- loading -----------------------------------------------------------
    def _frame_geometry(self):
        first_seq_frame = int(np.ceil(FS / self.n_hop))
        # +3 because of the centered STFT convention (datasets.py:73)
        n_frames_max = (TRAIN_DUR * FS - self.n_fft) // self.n_hop + 3 - first_seq_frame
        return first_seq_frame, n_frames_max

    def _load_rows(self, rows):
        """Load |STFT| of the given list rows into one (n_rows, n_seg, F, T)
        RAM array, dropping the first second (datasets.py:71-87).

        Uses the native threaded loader (disco_tpu/native/fastloader.cpp)
        when available — the reference's single-threaded np.load loop takes
        minutes over the 11k-RIR corpus; the C++ pool is IO-bound instead."""
        from disco_tpu.nn import fastload

        rows = list(rows)
        first_seq_frame, n_frames_max = self._frame_geometry()
        n_seg = len(self.segs_to_load[0])
        data = np.zeros((len(rows), n_seg, self.n_freq, n_frames_max), "float32")
        paths = [self.segs_to_load[row][i_seg] for row in rows for i_seg in range(n_seg)]
        flat = data.reshape(len(rows) * n_seg, self.n_freq, n_frames_max)
        _, frames = fastload.load_abs_batch(
            paths, self.n_freq, n_frames_max, skip_cols=first_seq_frame, out=flat
        )
        # per-segment geometry from the first row (datasets.py:83-86)
        n_frames = frames[:n_seg].astype("int")
        win_per_seg = (n_frames - self.win_len) // self.win_hop + 1
        return data, first_seq_frame, win_per_seg, n_frames

    def load_data(self):
        return self._load_rows(range(len(self.segs_to_load)))

    # -- item access -------------------------------------------------------
    def __len__(self):
        return int(sum(self.win_per_seg))

    def get_item_indices(self, item):
        """item → (segment k, first frame m) with random sub-hop jitter
        (datasets.py:105-118)."""
        k = int(np.searchsorted(self.n_cum, item, side="right")) - 1
        m = int(item - self.n_cum[k]) * self.win_hop + int(self.rng.integers(self.win_hop))
        m = min(m, int(self.n_frames[k]) - self.win_len)
        return k, m

    def _z_order(self, local_node):
        """Compressed-channel visit order: local node rolled last; a single
        z channel is drawn randomly among the others (datasets.py:134-140)."""
        z_chs = np.arange(self.n_nodes)
        if self.z_nodes == 1:
            z_chs = np.delete(z_chs, local_node)
            return self.rng.permutation(z_chs)
        return np.roll(z_chs, self.n_nodes - 1 - local_node)

    @property
    def _n_zsigs(self):
        # rows are [4 refs | 4 per zsig ... | 4 masks] (dnn/utils.py:98)
        return len(self.segs_to_load) // self.n_nodes - 2

    def _ref_window(self, local_node, k, m):
        return self.data[local_node, k, :, m : m + self.win_len]

    def _z_window(self, i_zsig, z_ch, k, m):
        return self.data[self.n_nodes * (i_zsig + 1) + z_ch, k, :, m : m + self.win_len]

    def get_mask_frames(self, local_node, k, m):
        return self.data[-self.n_nodes + local_node, k, :, m : m + self.win_len]

    def get_subwindow(self, local_node, k, m):
        """Input window: [local ref ‖ z's of other nodes] stacked per
        ``stack_axis``, plus the local mask label (datasets.py:120-151)."""
        mixt = [self._ref_window(local_node, k, m)]
        for z_ch in self._z_order(local_node)[: self.z_nodes]:
            for i_zsig in range(self._n_zsigs):
                mixt.append(self._z_window(i_zsig, int(z_ch), k, m))
        mixt = np.squeeze(np.array(mixt))
        if self.stack_axis == 1:
            mixt = np.concatenate([mixt[i] for i in range(mixt.shape[0])], axis=0)
        return np.abs(mixt), self.get_mask_frames(local_node, k, m)

    def __getitem__(self, item):
        k, m = self.get_item_indices(item)
        local_node = int(self.rng.integers(self.n_nodes))
        mixture, mask = self.get_subwindow(local_node, k, m)
        # (…, F, T) → (…, T, F) (datasets.py:102-103)
        return np.swapaxes(mixture, -2, -1), mask.T


class DiscoPartialDataset(DiscoDataset):
    """RAM holds only the z's; reference channels and masks are np.load-ed
    lazily per item (reference datasets.py:165-221)."""

    def load_data(self):
        rows = range(self.n_nodes, len(self.segs_to_load) - self.n_nodes)
        return self._load_rows(rows)

    def _ref_window(self, local_node, k, m):
        m_ = m + self.first_seq_frame
        return np.abs(np.load(self.segs_to_load[local_node][k])[:, m_ : m_ + self.win_len]).astype("float32")

    def _z_window(self, i_zsig, z_ch, k, m):
        return self.data[self.n_nodes * i_zsig + z_ch, k, :, m : m + self.win_len]

    def get_mask_frames(self, local_node, k, m):
        m_ = m + self.first_seq_frame
        return np.load(self.segs_to_load[-self.n_nodes + local_node][k])[:, m_ : m_ + self.win_len].astype("float32")


def batch_iterator(dataset, batch_size, shuffle=True, rng=None, drop_last=False):
    """Yield (x, y) numpy batches — the DataLoader equivalent feeding the
    jitted train step."""
    rng = rng or np.random.default_rng()
    order = rng.permutation(len(dataset)) if shuffle else np.arange(len(dataset))
    for start in range(0, len(order), batch_size):
        idx = order[start : start + batch_size]
        if drop_last and len(idx) < batch_size:
            return
        xs, ys = zip(*(dataset[int(i)] for i in idx))
        yield np.stack(xs), np.stack(ys)


# -- input lists (reference dnn/utils.py:74-140, dnn/data/lists_to_load.py) --
def get_input_lists(
    path_to_data,
    rirs_to_get,
    scenes=None,
    snr_range=None,
    noise_to_get="ssn",
    ref_channel=1,
    z_sigs=None,
    z_file="oracle",
    n_nodes=4,
    rng=None,
):
    """Per-signal lists of .npy paths: [4 mixture refs | 4 per z_sig |
    4 masks], one entry per RIR with a random scene and noise draw
    (reference dnn/utils.py:74-140)."""
    rng = rng or np.random.default_rng()
    scenes = ["random"] if scenes is None else scenes
    scenes = [scenes] if not isinstance(scenes, list) else scenes
    snr_range = [0, 6] if snr_range is None else snr_range
    z_sigs = [] if z_sigs is None else ([z_sigs] if not isinstance(z_sigs, list) else z_sigs)
    noise_pool = {
        "ssn": ["ssn"], "it": ["it"], "fs": ["fs"],
        "noit": ["ssn", "fs"], "all": ["ssn", "it", "fs"],
    }[noise_to_get]

    out = [[] for _ in range(n_nodes + len(z_sigs) * n_nodes + n_nodes)]
    for rir in rirs_to_get:
        scene = scenes[int(rng.integers(len(scenes)))]
        noise = noise_pool[int(rng.integers(len(noise_pool)))]
        lay = DatasetLayout(path_to_data, scene, "train")
        for node in range(n_nodes):
            ch = ref_channel + n_nodes * node
            out[node].append(str(lay.stft_processed(snr_range, "mixture", rir, ch, noise=noise, normed=True)))
            out[-n_nodes + node].append(str(lay.mask_processed(snr_range, rir, ch, noise)))
        for i_zsig, zsig in enumerate(z_sigs):
            for node in range(n_nodes):
                out[n_nodes + node + i_zsig * n_nodes].append(
                    str(lay.stft_z(z_file, snr_range, zsig, rir, node + 1, noise, normed=True))
                )
    return out


def write_input_lists(lists, folder):
    """Persist lists as one txt file per signal row — the rsync
    ``--files-from`` staging format (reference lists_to_load.py:27-40)."""
    os.makedirs(folder, exist_ok=True)
    for i, row in enumerate(lists):
        # atomic: a torn list file still parses (any line prefix is a valid
        # list), so a crash here would silently starve the loader instead
        # of erroring on resume
        with atomic_write(Path(folder, f"list_{i}.txt"), "w") as fh:
            fh.write("\n".join(row) + "\n")


def load_input_lists(folder):
    """Load lists written by :func:`write_input_lists`
    (reference lists_to_load.py:11-24)."""
    files = sorted(Path(folder).glob("list_*.txt"), key=lambda p: int(p.stem.split("_")[1]))
    return [p.read_text().splitlines() for p in files]
