"""The DNN stack: Flax CRNN mask estimator, data pipeline, training engine
(TPU-native counterpart of reference disco_theque/dnn/)."""
from disco_tpu.nn.bricks import CNN2d, FF, RNN, cnn_output_dim
from disco_tpu.nn.crnn import RNNMask, build_rnn, CRNN, build_crnn, loss_frame_bounds
from disco_tpu.nn.data import (
    DiscoDataset,
    DiscoPartialDataset,
    RandomDataset,
    batch_iterator,
    get_input_lists,
    load_input_lists,
    write_input_lists,
)
from disco_tpu.nn.losses import nanmean, reconstruction_loss
from disco_tpu.nn.training import (
    CheckpointError,
    SaveAndStop,
    TrainState,
    create_train_state,
    fit,
    get_model_name,
    load_checkpoint,
    load_params_for_inference,
    make_step_fns,
    replicate_to_mesh,
    save_checkpoint,
)

__all__ = [
    "CNN2d", "FF", "RNN", "cnn_output_dim",
    "CRNN", "build_crnn", "loss_frame_bounds",
    "DiscoDataset", "DiscoPartialDataset", "RandomDataset",
    "batch_iterator", "get_input_lists", "load_input_lists", "write_input_lists",
    "nanmean", "reconstruction_loss",
    "CheckpointError", "SaveAndStop", "TrainState", "create_train_state",
    "fit", "get_model_name",
    "load_checkpoint", "load_params_for_inference", "make_step_fns",
    "replicate_to_mesh", "save_checkpoint",
]
from disco_tpu.nn import fastload
