"""Training engine (reference dnn/engine/train.py, dnn/engine/callbacks.py,
dnn/utils.py:155-294).

The reference's per-batch torch logic (forward → frame-aligned masked-MSE →
RMSprop step, dnn/utils.py:249-294) becomes two jitted pure functions over a
``TrainState``; the epoch loop, best-model gate (``SaveAndStop``), loss-
history bookkeeping and checkpoint/resume semantics match
train.py:110-158 / callbacks.py:4-56.

Checkpoints serialize {params, batch_stats, opt_state, losses} with flax
msgpack — the orbax-free equivalent of the reference's
``torch.save({model_state_dict, optimizer_state_dict, train_loss,
val_loss})`` (train.py:147-156); resume splices the loss history exactly as
``load_states`` does (dnn/utils.py:155-175, np.trim_zeros).
"""
from __future__ import annotations

import string
import threading
import time
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization
from flax import struct
from flax.training import train_state

from disco_tpu.nn.losses import reconstruction_loss
from disco_tpu.obs import events as obs_events
from disco_tpu.obs.accounting import counted_jit, recompile_count
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.utils.transfer import prefetch_to_device


class TrainState(train_state.TrainState):
    """Optax train state + BatchNorm running statistics."""

    batch_stats: Any = None
    dropout_rng: Any = struct.field(pytree_node=True, default=None)


def create_train_state(model, tx, sample_input, seed=0):
    """Initialise parameters/batch stats from a sample batch.

    The ``step`` counter is materialized as a concrete int32 array up
    front: flax's ``TrainState.create`` leaves it a python int, which is a
    weak-typed leaf that differs from the int32 array every
    ``apply_gradients`` returns — so the FIRST train step of every run
    traced its own one-shot program (the weak-type twin of the mu=1
    retrace trap).  One dtype pin here keeps every lane at exactly one
    program, which the retrace-budget gate now holds exact."""
    init_rng, dropout_rng = jax.random.split(jax.random.PRNGKey(seed))
    variables = model.init({"params": init_rng, "dropout": dropout_rng}, jnp.asarray(sample_input))
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables.get("batch_stats", {}),
        dropout_rng=dropout_rng,
    )
    return state.replace(step=jnp.asarray(state.step, jnp.int32))


def _x_for_loss(x, bounds, n_freq=257):
    """Frame-align a tensor for the loss: first channel of 4-D inputs, frame
    slice, freq crop; single frames squeezed (reference
    dnn/utils.py:212-246)."""
    ff, lf = bounds
    if x.ndim == 4:
        x = x[:, 0]
    x = x[:, ff:lf, :n_freq]
    return x[:, 0, :] if lf - ff == 1 else x


#: memoized (train_step, eval_step) pairs keyed on
#: (model, output_frames, n_freq, mesh, canonical precision).  The memo is
#: what makes precision-spelling variants non-retracing — ' F32 ' and
#: 'f32' resolve to ONE key and therefore ONE pair of compiled programs
#: (the string-typed mu=1 retrace trap, closed at the factory) — and what
#: lets repeated ``fit`` calls share programs.  LRU-bounded: a
#: hyperparameter sweep building hundreds of distinct configs must not
#: pin every model + compiled executable forever (an evicted key simply
#: retraces, which the recompile counters make visible as always).
_STEP_FNS_MAX = 64
_STEP_FNS: dict = {}
_STEP_FNS_LOCK = threading.Lock()


def clear_step_fn_caches() -> None:
    """Clear the compiled-program caches of every memoized step-fn pair —
    the cold-cache seam the retrace-budget gate
    (``disco_tpu.analysis.trace.budgets``) needs to count fresh traces in
    an already-warm process.  The memo itself is kept: the budget asserts
    programs per LANE, not per factory call.

    No reference counterpart: the reference has no jit (SURVEY.md §5)."""
    with _STEP_FNS_LOCK:
        pairs = list(_STEP_FNS.values())
    for pair in pairs:
        for fn in pair:
            if getattr(fn, "clear_cache", None):
                fn.clear_cache()


def replicate_to_mesh(state: TrainState, mesh):
    """Replicate every TrainState leaf across ``mesh`` (params, optimizer
    accumulators and batch stats fully replicated — the data-parallel
    layout where only the batch axis of the data is sharded; the
    ``shard_params`` pattern of SNIPPETS [2] with ``P()`` specs).  The
    sharded ``train_step`` then keeps the replication invariant: XLA
    all-reduces the per-shard gradients and every device applies the same
    update.

    No reference counterpart: the reference trains on one process with
    torch (SURVEY.md §2.9)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(state, NamedSharding(mesh, P()))  # disco-lint: disable=DL003 -- TrainState leaves are real-dtyped (f32 params/stats, int32 step, uint32 rng); no complex array can reach this placement call


def make_step_fns(model, output_frames="all", n_freq=None, mesh=None,
                  precision="f32"):
    """(train_step, eval_step) jitted over TrainState + (x, y) batches
    (reference dnn/utils.py:249-294), memoized per
    ``(model, output_frames, n_freq, mesh, precision)``.

    ``mesh``: opt-in data-parallel lane — batches are constrained to
    ``NamedSharding(mesh, P("batch"))`` (the SNIPPETS [2] pattern through
    the same GSPMD formulation as ``parallel.mesh.tango_batch_sharded``),
    params stay replicated (:func:`replicate_to_mesh`), and the input
    ``TrainState`` is donated (``donate_argnames=("state",)`` — the
    corpus-engine donation rule applied to the training carry; ``fit``
    always rebinds, so the donated buffers are dead by construction).
    Degrades cleanly to a 1-device mesh, where the program is bit-exact
    with the meshless path (``make flywheel-check`` pins this).

    ``precision``: ``'f32'`` (default, the untouched original program) or
    ``'bf16'`` — mixed precision with bf16 apply-time params/activations
    and float32 master params, optimizer accumulators, batch stats and
    loss (the PR-9 enhancement-lane recipe on the training side).  The
    token is canonicalized through :func:`disco_tpu.ops.resolve.
    resolve_precision` BEFORE the memo key is formed, so spelling
    variants cannot trace duplicate programs; the retrace-budget gate
    holds the bf16 lane to exactly ONE extra program per step fn.
    """
    from disco_tpu.ops.resolve import compute_dtype, resolve_precision

    precision = resolve_precision(precision)
    key = (model, output_frames, n_freq, mesh, precision)
    with _STEP_FNS_LOCK:
        cached = _STEP_FNS.pop(key, None)
        if cached is not None:
            _STEP_FNS[key] = cached  # refresh recency (true LRU eviction)
    if cached is not None:
        return cached

    in_bounds, out_bounds = model.loss_frames(output_frames)
    n_freq = n_freq or model.input_shape[-1]
    cdtype = compute_dtype(precision)

    if mesh is not None:
        if "batch" not in mesh.axis_names:
            raise ValueError(
                f"data-parallel training needs a mesh with a 'batch' axis; "
                f"got axes {mesh.axis_names}"
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        batch_sharding = NamedSharding(mesh, P("batch"))

        def constrain(t):
            return jax.lax.with_sharding_constraint(t, batch_sharding)
    else:
        def constrain(t):
            return t

    def _cast_floats(tree, dtype):
        return jax.tree_util.tree_map(
            lambda a: a.astype(dtype)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
            tree,
        )

    def compute_loss(params, batch_stats, dropout_rng, x, y, train):
        x, y = constrain(x), constrain(y)
        if precision == "bf16":
            # bf16 apply-time copies; the f32 masters stay the grad target
            # (the cast is differentiable, so grads come back f32)
            apply_params = _cast_floats(params, cdtype)
            x_in = x.astype(cdtype)
        else:
            apply_params, x_in = params, x
        variables = {"params": apply_params, "batch_stats": batch_stats}
        if train:
            est, mutated = model.apply(
                variables, x_in, train=True, mutable=["batch_stats"], rngs={"dropout": dropout_rng}
            )
        else:
            est, mutated = model.apply(variables, x_in, train=False), None
        if precision == "bf16":
            # f32 accumulators: the loss and the carried batch stats must
            # not drift to bf16 (a bf16 stats pytree on step 2 would also
            # be a NEW program — the budget gate holds the lane to one)
            est = est.astype(jnp.float32)
            if mutated is not None:
                mutated = _cast_floats(mutated, jnp.float32)
        loss = reconstruction_loss(
            _x_for_loss(y, in_bounds, n_freq),
            _x_for_loss(est, out_bounds, n_freq),
            _x_for_loss(x, in_bounds, n_freq),
        )
        return loss, mutated

    # donate the carry on the sharded lane only: every mesh caller rebinds
    # (fit's loop), while the meshless entry points keep their historical
    # no-donation contract (tests step the same state freely)
    jit_kw = {"donate_argnames": ("state",)} if mesh is not None else {}

    @counted_jit(label="train_step", **jit_kw)
    def train_step(state: TrainState, x, y):
        dropout_rng, next_rng = jax.random.split(state.dropout_rng)
        (loss, mutated), grads = jax.value_and_grad(compute_loss, has_aux=True)(
            state.params, state.batch_stats, dropout_rng, x, y, True
        )
        state = state.apply_gradients(
            grads=grads, batch_stats=mutated["batch_stats"], dropout_rng=next_rng
        )
        return state, loss

    @counted_jit(label="eval_step")
    def eval_step(state: TrainState, x, y):
        loss, _ = compute_loss(state.params, state.batch_stats, state.dropout_rng, x, y, False)
        return loss

    with _STEP_FNS_LOCK:
        pair = _STEP_FNS.setdefault(key, (train_step, eval_step))
        if len(_STEP_FNS) > _STEP_FNS_MAX:  # evict least-recently-used
            _STEP_FNS.pop(next(iter(_STEP_FNS)))
        return pair


class SaveAndStop:
    """Best-model gate + early stopping (reference callbacks.py:4-56,
    with the shipped SyntaxError at :51 deliberately not reproduced —
    SURVEY.md §7 hard part 6)."""

    def __init__(self, patience=np.inf, mode="min", delta=0):
        if mode not in ("min", "max"):
            raise ValueError('`mode` can be only "min" or "max"')
        self.waited = 0
        self.patience = patience
        self.mode = mode
        self.delta = delta
        self.current_value = np.inf if mode == "min" else -np.inf

    def save_model_query(self, value):
        improved = (
            value < self.current_value - self.delta
            if self.mode == "min"
            else value > self.current_value + self.delta
        )
        if improved:
            self.current_value = value
            self.waited = 0
        else:
            self.waited += 1
        return improved

    def early_stop_query(self):
        return self.waited > self.patience


def get_model_name(model_name=None):
    """4-char pseudo-random run name; '_retrain' suffix on resume
    (reference dnn/utils.py:178-186)."""
    if model_name is None:
        chars = string.ascii_letters + string.digits
        seed = int(str(time.time()).replace(".", "")[-4:])
        return "".join(chars[(seed + 7 * i) % len(chars)] for i in range(4))
    return Path(model_name).name.split("_model")[0] + "_retrain"


# -- checkpointing ----------------------------------------------------------
class CheckpointError(RuntimeError):
    """A checkpoint file could not be restored (missing, truncated, or not
    a compatible msgpack payload).  Raised with the offending path in the
    message so CLIs can fail cleanly instead of surfacing a raw msgpack
    traceback."""


def save_checkpoint(path, state: TrainState, train_losses, val_losses,
                    epochs_done: int | None = None):
    """Serialize model+optimizer state and loss history to one msgpack file
    (the torch.save dict of reference train.py:147-156).  Written
    atomically (``disco_tpu.io.atomic``): a crash mid-save leaves the
    previous best checkpoint intact, never a truncated msgpack — the
    artifact a multi-hour training run resumes from must survive the crash
    that interrupts it.

    ``epochs_done`` is the number of completed epochs the (preallocated,
    zero-padded) loss histories cover, stored EXPLICITLY in the payload:
    the resume point used to be re-derived by trimming trailing zeros from
    the history (reference dnn/utils.py:155-175 ``np.trim_zeros``), which
    silently truncated it whenever a final epoch's loss was legitimately
    0.0.  The ``None`` default keeps direct callers working by recording
    the trimmed length — exactly the old inference, now frozen at save
    time; ``fit`` always passes the true count."""
    from disco_tpu.io.atomic import write_bytes_atomic

    if epochs_done is None:
        epochs_done = int(np.trim_zeros(np.asarray(train_losses), "b").size)
    payload = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": state.step,
        "train_loss": np.asarray(train_losses),
        "val_loss": np.asarray(val_losses),
        "epochs_done": np.asarray(int(epochs_done), np.int32),
    }
    write_bytes_atomic(path, serialization.to_bytes(payload))


def load_checkpoint(path, state: TrainState):
    """Restore a checkpoint into a compatible TrainState; returns
    (state, train_losses, val_losses) cut to the completed-epoch count
    (reference dnn/utils.py:155-175).

    The completed-epoch count is read from the payload's explicit
    ``epochs_done`` field when present (every checkpoint written since the
    flywheel PR); pre-flywheel checkpoints fall back to the historical
    ``np.trim_zeros`` inference — which is exactly the bug the explicit
    field fixes: a trailing epoch whose loss was legitimately 0.0 was
    indistinguishable from preallocated zero padding and silently moved
    the resume point backwards.

    Raises :class:`CheckpointError` naming ``path`` when the file is
    missing, truncated or not a compatible payload — a corrupt resume
    checkpoint must be a clean, actionable error, not an opaque msgpack
    traceback from deep inside flax."""
    try:
        raw = Path(path).read_bytes()
    except OSError as e:
        raise CheckpointError(f"checkpoint {path}: cannot read: {e}") from e
    template = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": state.step,
        "train_loss": np.zeros(0, np.float64),
        "val_loss": np.zeros(0, np.float64),
    }
    # flax's from_bytes template-matches strictly, so the new-format read
    # (with the explicit epochs_done field) is attempted first and a
    # pre-flywheel checkpoint falls back to the old template — one full
    # parse for every current file, two only for legacy ones (never a
    # whole msgpack_restore just to peek at the keys)
    has_count = True
    try:
        payload = serialization.from_bytes(
            {**template, "epochs_done": np.zeros((), np.int32)}, raw
        )
    except Exception:
        has_count = False
        try:
            payload = serialization.from_bytes(template, raw)
        except Exception as e:
            raise CheckpointError(
                f"checkpoint {path}: corrupt or incompatible msgpack payload "
                f"({type(e).__name__}: {e}) — the file may be truncated by a "
                f"crashed writer; delete it or point --weights at an intact "
                f"checkpoint"
            ) from e
    state = state.replace(
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
        step=payload["step"],
    )
    train_hist = np.asarray(payload["train_loss"])
    val_hist = np.asarray(payload["val_loss"])
    if has_count:
        n = max(0, min(int(payload["epochs_done"]), train_hist.size))
        return state, train_hist[:n], val_hist[: min(n, val_hist.size)]
    return (
        state,
        np.trim_zeros(train_hist, "b"),
        np.trim_zeros(val_hist, "b"),
    )


def load_params_for_inference(path, state: TrainState) -> TrainState:
    """Weights-only restore for enhancement-time mask estimation
    (reference tango.py:133-134)."""
    state, _, _ = load_checkpoint(path, state)
    return state


def publish_checkpoint(promote_dir, ckpt_path, *, arch: dict, ledger=None,
                       source: str | None = None):
    """THE publish seam between training and live promotion: stage one
    ``save_checkpoint`` file as an immutable weight generation under
    ``promote_dir`` (:meth:`disco_tpu.promote.store.GenerationStore.
    stage_checkpoint`).  ``arch``: the ``build_crnn`` kwargs the weights
    were trained with; ``ledger``: the training run's ledger (path or
    :class:`~disco_tpu.runs.RunLedger`) — a run whose latest ``epoch:*``
    unit is still ``in_flight`` (a mid-epoch-interrupted trainer: the
    checkpoint on disk predates the interrupted epoch) is refused with
    :class:`~disco_tpu.promote.store.PublishRefused` naming the unit.
    Returns the staged :class:`~disco_tpu.promote.store.Generation`.

    No reference counterpart: the reference trains once to a bare file
    (SURVEY.md §5.1)."""
    from disco_tpu.promote.store import GenerationStore

    ledger_path = getattr(ledger, "path", ledger)
    return GenerationStore(promote_dir).stage_checkpoint(
        ckpt_path, arch=arch, ledger=ledger_path,
        source=source or str(ckpt_path))


# -- the epoch loop ---------------------------------------------------------
def _prefetch_host_batches(make_batches):
    """Double-buffered host batch feed: batch N+1's numpy prep (shard
    reads, windowing, stacking) runs on a
    :class:`~disco_tpu.enhance.pipeline.ChunkPrefetcher` loader thread
    while step N's device compute runs, and the stall/overlap economics
    land in the SAME obs gauges the corpus engine records
    (``prefetch_stall_ms`` / ``overlap_efficiency`` via
    :func:`~disco_tpu.enhance.pipeline.note_chunk_overlap`) so the
    training-side overlap is observable and testable.  The loader is
    host-only (it never enters jax) and is always closed on unwind — an
    early stop mid-epoch must not leave it blocked on a full queue.

    Reference: train.py:104-105 reaches for torch DataLoader workers for
    exactly this host/device overlap."""
    from disco_tpu.enhance.pipeline import ChunkPrefetcher, note_chunk_overlap

    pf = ChunkPrefetcher(((b,) for b in make_batches()), lambda b: b, depth=2)
    try:
        last = time.perf_counter()
        for batch, stall_s in pf:
            busy_s = max(time.perf_counter() - last - stall_s, 0.0)
            note_chunk_overlap(stall_s, busy_s)
            yield batch
            last = time.perf_counter()
    finally:
        pf.close()


def fit(
    model,
    state: TrainState,
    train_batches,
    val_batches,
    n_epochs: int,
    save_path: str = "models/",
    run_name: str | None = None,
    output_frames: str = "all",
    resume_from: str | None = None,
    patience: float | None = None,
    verbose: bool = True,
    ledger=None,
    mesh=None,
    precision: str = "f32",
    promote_dir=None,
    promote_arch: dict | None = None,
):
    """Full training loop (reference train.py:110-158): per-epoch train +
    no-grad validation, loss history saved every epoch, best-model
    checkpoint gated by ``SaveAndStop``, optional early stop and resume.

    ``train_batches`` / ``val_batches`` are callables returning an iterator
    of (x, y) numpy batches (fresh shuffle each epoch).  Each epoch's
    batches ride a double-buffered host prefetch
    (:func:`_prefetch_host_batches` — the corpus engine's ChunkPrefetcher)
    into :func:`~disco_tpu.utils.transfer.prefetch_to_device`, so numpy
    batch prep, host→device transfer and device compute overlap.
    Returns (state, train_losses, val_losses, run_name).

    ``mesh`` / ``precision`` (the flywheel training lanes, see
    :func:`make_step_fns`): a mesh with a 'batch' axis arms data-parallel
    steps — the state is replicated (:func:`replicate_to_mesh`), batches
    shard over the mesh's batch axis, the carry is donated.  ``precision=
    'bf16'`` arms the mixed-precision lane (f32 masters/accumulators).

    Crash safety (``disco_tpu.runs``): checkpoints and loss histories are
    written atomically; an optional ``ledger``
    (:class:`~disco_tpu.runs.RunLedger` or path) records per-epoch
    in_flight/done transitions with artifact digests; a graceful stop
    (SIGTERM/SIGINT) finishes the current epoch — its losses and any
    improved checkpoint persist — and returns early, resumable via
    ``resume_from``.

    ``promote_dir`` (with ``promote_arch``, the ``build_crnn`` kwargs):
    the live publish seam — every improved checkpoint is additionally
    staged as a weight generation (:func:`publish_checkpoint`) AFTER its
    epoch's ledger record lands, so a serving promotion controller
    watching the store can canary it while this trainer keeps running.
    """
    from disco_tpu.runs import chaos as run_chaos
    from disco_tpu.runs import interrupt as run_interrupt
    from disco_tpu.runs.ledger import RunLedger, unit_epoch

    if ledger is not None and not isinstance(ledger, RunLedger):
        ledger = RunLedger(ledger)
    if promote_dir is not None and promote_arch is None:
        raise ValueError(
            "fit(promote_dir=...) needs promote_arch (the build_crnn "
            "kwargs) to stage generations with")
    train_step, eval_step = make_step_fns(model, output_frames, mesh=mesh,
                                          precision=precision)
    save_dir = Path(save_path)
    save_dir.mkdir(parents=True, exist_ok=True)

    if resume_from is not None:
        state, train_hist, val_hist = load_checkpoint(resume_from, state)
        first_epoch = len(train_hist)
        train_losses = np.concatenate([train_hist, np.zeros(n_epochs)])
        val_losses = np.concatenate([val_hist, np.zeros(n_epochs)])
        run_name = run_name or get_model_name(resume_from)
    else:
        first_epoch = 0
        train_losses, val_losses = np.zeros(n_epochs), np.zeros(n_epochs)
        run_name = run_name or get_model_name()

    if mesh is not None:
        # data-parallel invariant: replicated state, sharded batches
        state = replicate_to_mesh(state, mesh)

    # epoch-aware batch sources (flywheel ShardDataset.batch_fn): tell them
    # where training actually starts, so a resumed run's dataset epochs —
    # shuffle draws AND ledger shard:*:epoch:<e> consumption units — line
    # up with the training epochs instead of replaying from 0 (which, with
    # a reused dataset ledger, would yield zero batches for every
    # already-consumed epoch and silently train on nothing)
    for cb in (train_batches, val_batches):
        hook = getattr(cb, "set_start_epoch", None)
        if hook is not None:
            hook(first_epoch)

    gate = SaveAndStop(patience=patience if patience is not None else n_epochs, mode="min")
    # Per-label counts, not the process-wide total: an unrelated retrace
    # elsewhere (e.g. an enhancement pass sharing the process) must not be
    # charged to an epoch's `recompiles` attribute.
    _fit_recompiles = lambda: recompile_count("train_step") + recompile_count("eval_step")
    recompiles0 = _fit_recompiles()
    interrupted = False
    for epoch in range(first_epoch, first_epoch + n_epochs):
        if run_interrupt.stop_requested():
            # Graceful stop between epochs: everything already on disk
            # (atomic), resumable via resume_from on the saved checkpoint.
            interrupted = True
            break
        if ledger is not None:
            ledger.mark_in_flight(unit_epoch(epoch))
        t_epoch = time.perf_counter()
        # Losses stay ON DEVICE across the epoch as a running sum: a
        # float() per step would fence the pipeline (host sync per batch),
        # serializing host batch prep against device compute.  With async
        # dispatch + the prefetch feed, step N+1's data is ready while
        # step N runs; one readback per epoch.
        tr, nb = jnp.zeros(()), 0
        for x, y in prefetch_to_device(_prefetch_host_batches(train_batches)):
            state, loss = train_step(state, x, y)
            tr = tr + loss
            nb += 1
        # mid_epoch chaos seam: crash with the train pass done but nothing
        # persisted — the whole epoch must be redone on resume, never half
        run_chaos.tick("mid_epoch", epoch=int(epoch))
        va, nv = jnp.zeros(()), 0
        for x, y in prefetch_to_device(_prefetch_host_batches(val_batches)):
            va = va + eval_step(state, x, y)
            nv += 1
        if nb == 0:
            # an epoch that saw NO training batches is almost always an
            # operator error (e.g. a reused dataset ledger whose shard
            # units are all consumed — rerun with a fresh --ledger or
            # resume with --weights): it must be loud, or the run records
            # 0.0 losses and checkpoints an untrained model as 'best'
            obs_registry.counter("train_empty_epochs").inc()
            obs_events.record(
                "warning", stage="train", epoch=int(epoch),
                reason="epoch yielded ZERO training batches — empty "
                       "dataset, or a reused dataset ledger already marks "
                       "every shard consumed for this epoch",
            )
            if verbose:
                print(f"epoch {epoch}\tWARNING: zero training batches "
                      "(empty dataset or fully-consumed dataset ledger)")
        train_losses[epoch] = float(tr) / nb if nb else 0.0
        val_losses[epoch] = float(va) / nv if nv else 0.0
        obs_registry.counter("train_steps").inc(nb)
        obs_registry.gauge("train_loss").set(train_losses[epoch])
        obs_registry.gauge("val_loss").set(val_losses[epoch])
        if obs_events.enabled():
            recompiles = _fit_recompiles()
            obs_events.record(
                "epoch", stage="train", epoch=int(epoch),
                train_loss=train_losses[epoch], val_loss=val_losses[epoch],
                steps=nb, val_batches=nv,
                dur_s=round(time.perf_counter() - t_epoch, 6),
                recompiles=recompiles - recompiles0,
            )
            recompiles0 = recompiles
        if verbose:
            print(f"epoch {epoch}\tTrain\t{train_losses[epoch]:.6f}\tVal\t{val_losses[epoch]:.6f}")
        from disco_tpu.io.atomic import savez_atomic

        losses_path = savez_atomic(
            save_dir / f"{run_name}_losses.npz",
            train_loss=train_losses, val_loss=val_losses,
        )
        ckpt_path = save_dir / f"{run_name}_model.msgpack"
        improved = gate.save_model_query(val_losses[epoch])
        if improved:
            save_checkpoint(ckpt_path, state, train_losses, val_losses,
                            epochs_done=int(epoch) + 1)
        if ledger is not None:
            # Epoch records are state-only (artifacts=None): the losses npz
            # and best checkpoint are SHARED mutable files that later epochs
            # overwrite, so digesting them into each epoch's done record
            # would falsely void every epoch but the last on resume.  The
            # current checkpoint digest rides along as informational attrs —
            # it is exactly the file a --weights resume restarts from.
            from disco_tpu.io.atomic import file_digest

            ledger.record(
                unit_epoch(epoch), "done",
                train_loss=float(train_losses[epoch]),
                val_loss=float(val_losses[epoch]), improved=improved,
                losses=str(losses_path),
                ckpt=str(ckpt_path) if improved else None,
                ckpt_digest=file_digest(ckpt_path) if improved else None,
            )
        if improved and promote_dir is not None:
            # publish AFTER the epoch's done record: the staging-side
            # ledger check reads this run's ledger, and an in_flight unit
            # here would (correctly) refuse the freshly-written checkpoint
            from disco_tpu.promote.store import PublishRefused

            try:
                gen = publish_checkpoint(promote_dir, ckpt_path,
                                         arch=promote_arch, ledger=ledger)
                obs_events.record("promotion", stage="train",
                                  action="published", gen=gen.gen_id,
                                  epoch=int(epoch))
            except PublishRefused as e:
                obs_events.record("promotion", stage="train",
                                  action="refused", unit=e.unit,
                                  reason=str(e))
        if gate.early_stop_query():
            break
    if interrupted:
        obs_events.record(
            "note", stage="train",
            reason="graceful stop: training wound down between epochs; "
                   "resume with --weights on the saved checkpoint",
        )
    return state, train_losses, val_losses, run_name
