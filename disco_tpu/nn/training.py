"""Training engine (reference dnn/engine/train.py, dnn/engine/callbacks.py,
dnn/utils.py:155-294).

The reference's per-batch torch logic (forward → frame-aligned masked-MSE →
RMSprop step, dnn/utils.py:249-294) becomes two jitted pure functions over a
``TrainState``; the epoch loop, best-model gate (``SaveAndStop``), loss-
history bookkeeping and checkpoint/resume semantics match
train.py:110-158 / callbacks.py:4-56.

Checkpoints serialize {params, batch_stats, opt_state, losses} with flax
msgpack — the orbax-free equivalent of the reference's
``torch.save({model_state_dict, optimizer_state_dict, train_loss,
val_loss})`` (train.py:147-156); resume splices the loss history exactly as
``load_states`` does (dnn/utils.py:155-175, np.trim_zeros).
"""
from __future__ import annotations

import string
import time
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization
from flax import struct
from flax.training import train_state

from disco_tpu.nn.losses import reconstruction_loss
from disco_tpu.obs import events as obs_events
from disco_tpu.obs.accounting import counted_jit, recompile_count
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.utils.transfer import prefetch_to_device


class TrainState(train_state.TrainState):
    """Optax train state + BatchNorm running statistics."""

    batch_stats: Any = None
    dropout_rng: Any = struct.field(pytree_node=True, default=None)


def create_train_state(model, tx, sample_input, seed=0):
    """Initialise parameters/batch stats from a sample batch."""
    init_rng, dropout_rng = jax.random.split(jax.random.PRNGKey(seed))
    variables = model.init({"params": init_rng, "dropout": dropout_rng}, jnp.asarray(sample_input))
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables.get("batch_stats", {}),
        dropout_rng=dropout_rng,
    )


def _x_for_loss(x, bounds, n_freq=257):
    """Frame-align a tensor for the loss: first channel of 4-D inputs, frame
    slice, freq crop; single frames squeezed (reference
    dnn/utils.py:212-246)."""
    ff, lf = bounds
    if x.ndim == 4:
        x = x[:, 0]
    x = x[:, ff:lf, :n_freq]
    return x[:, 0, :] if lf - ff == 1 else x


def make_step_fns(model, output_frames="all", n_freq=None):
    """(train_step, eval_step) jitted over TrainState + (x, y) batches
    (reference dnn/utils.py:249-294)."""
    in_bounds, out_bounds = model.loss_frames(output_frames)
    n_freq = n_freq or model.input_shape[-1]

    def compute_loss(params, batch_stats, dropout_rng, x, y, train):
        variables = {"params": params, "batch_stats": batch_stats}
        if train:
            est, mutated = model.apply(
                variables, x, train=True, mutable=["batch_stats"], rngs={"dropout": dropout_rng}
            )
        else:
            est, mutated = model.apply(variables, x, train=False), None
        loss = reconstruction_loss(
            _x_for_loss(y, in_bounds, n_freq),
            _x_for_loss(est, out_bounds, n_freq),
            _x_for_loss(x, in_bounds, n_freq),
        )
        return loss, mutated

    @counted_jit(label="train_step")
    def train_step(state: TrainState, x, y):
        dropout_rng, next_rng = jax.random.split(state.dropout_rng)
        (loss, mutated), grads = jax.value_and_grad(compute_loss, has_aux=True)(
            state.params, state.batch_stats, dropout_rng, x, y, True
        )
        state = state.apply_gradients(
            grads=grads, batch_stats=mutated["batch_stats"], dropout_rng=next_rng
        )
        return state, loss

    @counted_jit(label="eval_step")
    def eval_step(state: TrainState, x, y):
        loss, _ = compute_loss(state.params, state.batch_stats, state.dropout_rng, x, y, False)
        return loss

    return train_step, eval_step


class SaveAndStop:
    """Best-model gate + early stopping (reference callbacks.py:4-56,
    with the shipped SyntaxError at :51 deliberately not reproduced —
    SURVEY.md §7 hard part 6)."""

    def __init__(self, patience=np.inf, mode="min", delta=0):
        if mode not in ("min", "max"):
            raise ValueError('`mode` can be only "min" or "max"')
        self.waited = 0
        self.patience = patience
        self.mode = mode
        self.delta = delta
        self.current_value = np.inf if mode == "min" else -np.inf

    def save_model_query(self, value):
        improved = (
            value < self.current_value - self.delta
            if self.mode == "min"
            else value > self.current_value + self.delta
        )
        if improved:
            self.current_value = value
            self.waited = 0
        else:
            self.waited += 1
        return improved

    def early_stop_query(self):
        return self.waited > self.patience


def get_model_name(model_name=None):
    """4-char pseudo-random run name; '_retrain' suffix on resume
    (reference dnn/utils.py:178-186)."""
    if model_name is None:
        chars = string.ascii_letters + string.digits
        seed = int(str(time.time()).replace(".", "")[-4:])
        return "".join(chars[(seed + 7 * i) % len(chars)] for i in range(4))
    return Path(model_name).name.split("_model")[0] + "_retrain"


# -- checkpointing ----------------------------------------------------------
class CheckpointError(RuntimeError):
    """A checkpoint file could not be restored (missing, truncated, or not
    a compatible msgpack payload).  Raised with the offending path in the
    message so CLIs can fail cleanly instead of surfacing a raw msgpack
    traceback."""


def save_checkpoint(path, state: TrainState, train_losses, val_losses):
    """Serialize model+optimizer state and loss history to one msgpack file
    (the torch.save dict of reference train.py:147-156).  Written
    atomically (``disco_tpu.io.atomic``): a crash mid-save leaves the
    previous best checkpoint intact, never a truncated msgpack — the
    artifact a multi-hour training run resumes from must survive the crash
    that interrupts it."""
    from disco_tpu.io.atomic import write_bytes_atomic

    payload = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": state.step,
        "train_loss": np.asarray(train_losses),
        "val_loss": np.asarray(val_losses),
    }
    write_bytes_atomic(path, serialization.to_bytes(payload))


def load_checkpoint(path, state: TrainState):
    """Restore a checkpoint into a compatible TrainState; returns
    (state, train_losses, val_losses) with trailing zero-padding trimmed
    (reference dnn/utils.py:155-175).

    Raises :class:`CheckpointError` naming ``path`` when the file is
    missing, truncated or not a compatible payload — a corrupt resume
    checkpoint must be a clean, actionable error, not an opaque msgpack
    traceback from deep inside flax."""
    try:
        raw = Path(path).read_bytes()
    except OSError as e:
        raise CheckpointError(f"checkpoint {path}: cannot read: {e}") from e
    template = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": state.step,
        "train_loss": np.zeros(0, np.float64),
        "val_loss": np.zeros(0, np.float64),
    }
    try:
        payload = serialization.from_bytes(template, raw)
    except Exception as e:
        raise CheckpointError(
            f"checkpoint {path}: corrupt or incompatible msgpack payload "
            f"({type(e).__name__}: {e}) — the file may be truncated by a "
            f"crashed writer; delete it or point --weights at an intact "
            f"checkpoint"
        ) from e
    state = state.replace(
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
        step=payload["step"],
    )
    return (
        state,
        np.trim_zeros(np.asarray(payload["train_loss"]), "b"),
        np.trim_zeros(np.asarray(payload["val_loss"]), "b"),
    )


def load_params_for_inference(path, state: TrainState) -> TrainState:
    """Weights-only restore for enhancement-time mask estimation
    (reference tango.py:133-134)."""
    state, _, _ = load_checkpoint(path, state)
    return state


# -- the epoch loop ---------------------------------------------------------
def fit(
    model,
    state: TrainState,
    train_batches,
    val_batches,
    n_epochs: int,
    save_path: str = "models/",
    run_name: str | None = None,
    output_frames: str = "all",
    resume_from: str | None = None,
    patience: float | None = None,
    verbose: bool = True,
    ledger=None,
):
    """Full training loop (reference train.py:110-158): per-epoch train +
    no-grad validation, loss history saved every epoch, best-model
    checkpoint gated by ``SaveAndStop``, optional early stop and resume.

    ``train_batches`` / ``val_batches`` are callables returning an iterator
    of (x, y) numpy batches (fresh shuffle each epoch).
    Returns (state, train_losses, val_losses, run_name).

    Crash safety (``disco_tpu.runs``): checkpoints and loss histories are
    written atomically; an optional ``ledger``
    (:class:`~disco_tpu.runs.RunLedger` or path) records per-epoch
    in_flight/done transitions with artifact digests; a graceful stop
    (SIGTERM/SIGINT) finishes the current epoch — its losses and any
    improved checkpoint persist — and returns early, resumable via
    ``resume_from``.
    """
    from disco_tpu.runs import chaos as run_chaos
    from disco_tpu.runs import interrupt as run_interrupt
    from disco_tpu.runs.ledger import RunLedger, unit_epoch

    if ledger is not None and not isinstance(ledger, RunLedger):
        ledger = RunLedger(ledger)
    train_step, eval_step = make_step_fns(model, output_frames)
    save_dir = Path(save_path)
    save_dir.mkdir(parents=True, exist_ok=True)

    if resume_from is not None:
        state, train_hist, val_hist = load_checkpoint(resume_from, state)
        first_epoch = len(train_hist)
        train_losses = np.concatenate([train_hist, np.zeros(n_epochs)])
        val_losses = np.concatenate([val_hist, np.zeros(n_epochs)])
        run_name = run_name or get_model_name(resume_from)
    else:
        first_epoch = 0
        train_losses, val_losses = np.zeros(n_epochs), np.zeros(n_epochs)
        run_name = run_name or get_model_name()

    gate = SaveAndStop(patience=patience if patience is not None else n_epochs, mode="min")
    # Per-label counts, not the process-wide total: an unrelated retrace
    # elsewhere (e.g. an enhancement pass sharing the process) must not be
    # charged to an epoch's `recompiles` attribute.
    _fit_recompiles = lambda: recompile_count("train_step") + recompile_count("eval_step")
    recompiles0 = _fit_recompiles()
    interrupted = False
    for epoch in range(first_epoch, first_epoch + n_epochs):
        if run_interrupt.stop_requested():
            # Graceful stop between epochs: everything already on disk
            # (atomic), resumable via resume_from on the saved checkpoint.
            interrupted = True
            break
        if ledger is not None:
            ledger.mark_in_flight(unit_epoch(epoch))
        t_epoch = time.perf_counter()
        # Losses stay ON DEVICE across the epoch as a running sum: a
        # float() per step would fence the pipeline (host sync per batch),
        # serializing host batch prep against device compute.  With async
        # dispatch + the prefetch feed, step N+1's data is ready while
        # step N runs; one readback per epoch.
        tr, nb = jnp.zeros(()), 0
        for x, y in prefetch_to_device(train_batches()):
            state, loss = train_step(state, x, y)
            tr = tr + loss
            nb += 1
        # mid_epoch chaos seam: crash with the train pass done but nothing
        # persisted — the whole epoch must be redone on resume, never half
        run_chaos.tick("mid_epoch", epoch=int(epoch))
        va, nv = jnp.zeros(()), 0
        for x, y in prefetch_to_device(val_batches()):
            va = va + eval_step(state, x, y)
            nv += 1
        train_losses[epoch] = float(tr) / nb if nb else 0.0
        val_losses[epoch] = float(va) / nv if nv else 0.0
        obs_registry.counter("train_steps").inc(nb)
        obs_registry.gauge("train_loss").set(train_losses[epoch])
        obs_registry.gauge("val_loss").set(val_losses[epoch])
        if obs_events.enabled():
            recompiles = _fit_recompiles()
            obs_events.record(
                "epoch", stage="train", epoch=int(epoch),
                train_loss=train_losses[epoch], val_loss=val_losses[epoch],
                steps=nb, val_batches=nv,
                dur_s=round(time.perf_counter() - t_epoch, 6),
                recompiles=recompiles - recompiles0,
            )
            recompiles0 = recompiles
        if verbose:
            print(f"epoch {epoch}\tTrain\t{train_losses[epoch]:.6f}\tVal\t{val_losses[epoch]:.6f}")
        from disco_tpu.io.atomic import savez_atomic

        losses_path = savez_atomic(
            save_dir / f"{run_name}_losses.npz",
            train_loss=train_losses, val_loss=val_losses,
        )
        ckpt_path = save_dir / f"{run_name}_model.msgpack"
        improved = gate.save_model_query(val_losses[epoch])
        if improved:
            save_checkpoint(ckpt_path, state, train_losses, val_losses)
        if ledger is not None:
            # Epoch records are state-only (artifacts=None): the losses npz
            # and best checkpoint are SHARED mutable files that later epochs
            # overwrite, so digesting them into each epoch's done record
            # would falsely void every epoch but the last on resume.  The
            # current checkpoint digest rides along as informational attrs —
            # it is exactly the file a --weights resume restarts from.
            from disco_tpu.io.atomic import file_digest

            ledger.record(
                unit_epoch(epoch), "done",
                train_loss=float(train_losses[epoch]),
                val_loss=float(val_losses[epoch]), improved=improved,
                losses=str(losses_path),
                ckpt=str(ckpt_path) if improved else None,
                ckpt_digest=file_digest(ckpt_path) if improved else None,
            )
        if gate.early_stop_query():
            break
    if interrupted:
        obs_events.record(
            "note", stage="train",
            reason="graceful stop: training wound down between epochs; "
                   "resume with --weights on the saved checkpoint",
        )
    return state, train_losses, val_losses, run_name
