"""CRNN training CLI.

Mirrors reference ``dnn/engine/train.py:19-158`` (flags --scene/--noise/
--zsigs/--weights/--files_to_load/--zfile/--n_files/--n_epochs/--path_data,
hard-coded hyperparameters train.py:66-85), with the flax/optax training
stack: jitted train/eval steps, SaveAndStop best-checkpoint gating, early
stop and resume."""
from __future__ import annotations

import argparse

import numpy as np

from disco_tpu.cli.common import (
    add_ledger_arg,
    add_obs_log_arg,
    add_preflight_arg,
    add_trace_dir_arg,
    none_str,
    obs_session,
    run_preflight,
)
from disco_tpu.config import TrainConfig
from disco_tpu.nn.crnn import build_crnn
from disco_tpu.nn.data import (
    DiscoDataset,
    get_input_lists,
    load_input_lists,
)
from disco_tpu.nn.training import create_train_state, fit


def build_parser():
    """Build the ``disco-train`` argument parser."""
    p = argparse.ArgumentParser(description="Train the mask-estimation CRNN")
    p.add_argument("--archi", choices=["crnn", "rnn"], default="crnn",
                   help="mask estimator: CRNN (3-D windows) or 2-D RNN (freq-stacked)")
    p.add_argument("--scene", default="living")
    p.add_argument("--noise", choices=["ssn", "it", "fs", "noit", "all"], default="ssn")
    p.add_argument("--zsigs", "-zs", nargs="+", default=["zs_hat"])
    p.add_argument("--weights", "-w", default="None", help="resume checkpoint path")
    p.add_argument("--files_to_load", "-f2l", default="None", help="folder of persisted input lists")
    p.add_argument("--zfile", "-zf", default="oracle", help="z export name under stft_z/")
    p.add_argument("--n_files", "-n", type=int, default=11001, help="number of training sequences")
    p.add_argument("--n_epochs", "-epo", type=int, default=150)
    p.add_argument("--path_data", "-path", default="dataset/disco/")
    p.add_argument("--save_path", default="models/")
    p.add_argument("--batch_size", type=int, default=None, help="override the canonical 500")
    p.add_argument("--single_channel", "-sc", action="store_true",
                   help="train the step-1 single-channel model (no z inputs)")
    p.add_argument("--seed", type=int, default=26, help="train.py:20 seed")
    p.add_argument("--shards", default=None, metavar="DIR",
                   help="train on a flywheel shard directory (the serve "
                        "tap's --tap-dir output, disco_tpu.flywheel) "
                        "instead of the pre-generated corpus: streaming "
                        "reader with deterministic seeded shuffle, ledger "
                        "resume (--ledger) and corrupt-shard "
                        "skip-with-warning; the model is sized from the "
                        "shards' geometry")
    p.add_argument("--shard-win-len", type=int, default=None,
                   help="frames per training window on the --shards path "
                        "(default: the tapped block length; must fit "
                        "inside one block)")
    p.add_argument("--data-parallel", type=int, default=0, metavar="N",
                   help="shard the batch axis over an N-device mesh "
                        "(NamedSharding(mesh, P('batch')) through "
                        "parallel/mesh; params replicated, TrainState "
                        "donated; 0 = single device).  Degrades cleanly "
                        "to a 1-device mesh")
    p.add_argument("--precision", default="f32", choices=["f32", "bf16"],
                   help="training compute lane (ops.resolve): 'bf16' arms "
                        "mixed precision — bf16 apply-time params and "
                        "activations, float32 master params, optimizer "
                        "accumulators and loss")
    p.add_argument("--promote-dir", default=None, metavar="DIR",
                   help="publish every improved-best checkpoint as a "
                        "digest-addressed weight generation under DIR "
                        "(disco_tpu.promote), where a live disco-serve "
                        "--promote-dir server canaries and promotes it; "
                        "mid-epoch checkpoints of an interrupted run are "
                        "refused by the ledger check, never staged "
                        "(CRNN only)")
    add_ledger_arg(p, "epoch")
    add_preflight_arg(p, what="the multi-hour run")
    add_obs_log_arg(p, what="training")
    add_trace_dir_arg(p)
    return p


def main(argv=None):
    """``disco-train`` console entry point."""
    args = build_parser().parse_args(argv)
    with obs_session(args, tool="disco-train"):
        preflight = run_preflight(args)
        from disco_tpu import obs as _obs

        _obs.record("run_start", stage="train", tool="disco-train",
                    preflight=preflight, ledger=args.ledger,
                    resume=none_str(args.weights) is not None)
        from disco_tpu.nn.training import CheckpointError
        from disco_tpu.runs import GracefulInterrupt

        try:
            with GracefulInterrupt() as stopped:
                out = _run(args)
            if stopped():
                print("interrupted — training wound down between epochs; resume "
                      "with --weights on the saved checkpoint")
            return out
        except CheckpointError as e:
            # a corrupt/truncated --weights checkpoint is a clean CLI error
            # naming the path, never a raw msgpack traceback
            raise SystemExit(f"--weights: {e}")


def _mesh(args):
    """The --data-parallel training mesh (None at the 0 default) — a
    (batch, node=1) mesh through the parallel/mesh compat seams
    (reference: none; SURVEY.md §2.9 runs data parallelism as a process
    array)."""
    if not args.data_parallel:
        return None
    from disco_tpu.parallel.mesh import make_mesh

    return make_mesh(n_node=1, n_batch=args.data_parallel)


def _run_shards(args):
    """The flywheel path: train the single-channel mask CRNN on tapped
    serve traffic (disco_tpu.flywheel.ShardDataset).  No reference
    counterpart: the reference has no serving layer to learn from."""
    cfg = TrainConfig()
    from disco_tpu.flywheel import ShardDataset
    from disco_tpu.flywheel.dataset import peek_geometry

    geom = peek_geometry(args.shards)
    if geom is None:
        raise SystemExit(f"--shards {args.shards}: no intact shard files")
    win_len = args.shard_win_len or geom["block_frames"]
    if win_len > geom["block_frames"]:
        raise SystemExit(
            f"--shard-win-len {win_len} exceeds the tapped block length "
            f"{geom['block_frames']} (windows never cross block boundaries)"
        )
    ds = ShardDataset(args.shards, win_len=win_len, seed=args.seed)
    batch = args.batch_size or cfg.batch_size
    # the arch dict doubles as the generation-store architecture record
    # (--promote-dir): a serve-side GenerationStore.load rebuilds this
    # exact model from it
    arch = dict(n_ch=1, win_len=win_len, n_freq=geom["n_freq"],
                learning_rate=cfg.lr, ff_units=(geom["n_freq"],))
    model, tx = build_crnn(**arch)
    if model.conv_output_hw()[0] < 1:
        raise SystemExit(
            f"--shard-win-len {win_len} is too short for the canonical CRNN "
            "conv stack (three VALID 3-kernels eat 6 frames): use >= 7, or "
            "tap longer blocks — an empty loss slice trains on NaNs"
        )
    first = next(ds.batches(1, epoch=0), None)
    if first is None:
        raise SystemExit(f"--shards {args.shards}: shards hold no windows "
                         f"of {win_len} frames")
    state = create_train_state(model, tx, first[0], seed=args.seed)

    state, train_losses, val_losses, run_name = fit(
        model, state,
        ds.batch_fn(batch, shuffle=True, ledger=args.ledger),
        ds.batch_fn(batch, shuffle=False),
        n_epochs=args.n_epochs,
        save_path=args.save_path,
        output_frames=cfg.output_frames,
        resume_from=none_str(args.weights),
        patience=cfg.early_stop_patience,
        ledger=args.ledger,
        mesh=_mesh(args),
        precision=args.precision,
        promote_dir=args.promote_dir,
        promote_arch=arch if args.promote_dir else None,
    )
    print(f"run {run_name}: best val loss {np.nanmin(val_losses):.6f}")
    return run_name


def _run(args):
    if args.shards is not None:
        return _run_shards(args)
    cfg = TrainConfig()
    rng = np.random.default_rng(args.seed)

    z_sigs = None if args.single_channel else args.zsigs
    if none_str(args.files_to_load) is not None:
        lists = load_input_lists(args.files_to_load)
    else:
        lists = get_input_lists(
            args.path_data,
            rirs_to_get=range(1, args.n_files),
            scenes=[args.scene],
            noise_to_get=args.noise,
            z_sigs=z_sigs,
            z_file=args.zfile,
            rng=rng,
        )

    # single-channel: stack_axis 0; multichannel: z's on the channel axis
    # for the CRNN (3-D input) or on the freq axis for the 2-D RNN
    # (reference train.py:73-74)
    if args.single_channel:
        stack_axis = 0
    else:
        stack_axis = 2 if args.archi == "crnn" else 1
    dataset = DiscoDataset(
        lists, stack_axis=stack_axis, win_len=cfg.win_len, win_hop=cfg.win_hop, rng=rng
    )
    n_val = max(1, int(cfg.val_split * len(dataset)))
    idx = rng.permutation(len(dataset))
    val_idx, train_idx = idx[:n_val], idx[n_val:]
    batch = args.batch_size or cfg.batch_size

    def subset_batches(indices, shuffle):
        def gen():
            order = rng.permutation(indices) if shuffle else indices
            for start in range(0, len(order), batch):
                sel = order[start : start + batch]
                xs, ys = zip(*(dataset[int(i)] for i in sel))
                yield np.stack(xs), np.stack(ys)

        return gen

    n_ch = 1 if args.single_channel else 1 + dataset.z_nodes
    arch = dict(n_ch=n_ch, win_len=cfg.win_len, n_freq=cfg.ff_units,
                learning_rate=cfg.lr)
    if args.archi == "crnn":
        model, tx = build_crnn(**arch)
    else:
        if args.promote_dir:
            raise SystemExit(
                "--promote-dir: only the CRNN architecture can be staged "
                "as a serve weight generation (the serve model lane "
                "rebuilds via build_crnn); drop --promote-dir or use "
                "--archi crnn"
            )
        from disco_tpu.nn.crnn import build_rnn

        model, tx = build_rnn(n_ch=n_ch, win_len=cfg.win_len, n_freq=cfg.ff_units, learning_rate=cfg.lr)
    x0, _ = dataset[0]
    state = create_train_state(model, tx, x0[None], seed=args.seed)

    import contextlib

    from disco_tpu.utils import trace_to

    trace_cm = trace_to(args.trace_dir) if args.trace_dir else contextlib.nullcontext()
    with trace_cm:
        state, train_losses, val_losses, run_name = fit(
            model, state,
            subset_batches(train_idx, shuffle=True),
            subset_batches(val_idx, shuffle=False),
            n_epochs=args.n_epochs,
            save_path=args.save_path,
            output_frames=cfg.output_frames,
            resume_from=none_str(args.weights),
            patience=cfg.early_stop_patience,
            ledger=args.ledger,
            promote_dir=args.promote_dir,
            promote_arch=arch if args.promote_dir else None,
        )
    print(f"run {run_name}: best val loss {np.nanmin(val_losses):.6f}")
    return run_name


if __name__ == "__main__":
    main()
