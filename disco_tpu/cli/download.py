"""Corpus download CLI.

Mirrors reference ``pre_generation/download_freesound_queries.py:81-108``
(--token/--config/--num_jobs + output dir) plus the csv cleaning entry of
``clean_audio_info.py`` and a ``--list-urls`` mode printing the LibriSpeech /
Zenodo sources of the published DISCO corpus for the host's own fetcher
(the zero-egress equivalent of download_librispeech.sh / zenodo.sh)."""
from __future__ import annotations

import argparse
import glob
import os

from disco_tpu.datagen.download import (
    LIBRISPEECH_URLS,
    ZENODO_DISCO_NOISE_URL,
    DownloadConfig,
    FreesoundInquirer,
    clean_info,
    download_freesound,
    get_missing,
    set_up_log,
)


def build_parser():
    """Build the ``disco-download`` argument parser."""
    p = argparse.ArgumentParser(description="Fetch DISCO corpus material (Freesound/LibriSpeech/Zenodo)")
    p.add_argument("--token", "-t", default=None, help="Freesound OAuth token")
    p.add_argument("--config", "-c", default=None, help="yaml download config")
    p.add_argument("--out", "-o", default="dataset/freesound/data/")
    p.add_argument("--num_jobs", "-j", type=int, default=1)
    p.add_argument("--clean", metavar="DIR", default=None,
                   help="reconcile csv info files under DIR instead of downloading")
    p.add_argument("--list-urls", action="store_true",
                   help="print LibriSpeech + Zenodo corpus URLs and exit")
    return p


def main(argv=None):
    """``disco-download`` console entry point."""
    args = build_parser().parse_args(argv)
    logger = set_up_log(level=1)

    if args.list_urls:
        for url in LIBRISPEECH_URLS + [ZENODO_DISCO_NOISE_URL]:
            print(url)
        return 0

    if args.clean:
        n = 0
        for csv_path in glob.iglob(os.path.join(args.clean, "**", "*.csv"), recursive=True):
            missing = get_missing(csv_path)
            if missing:
                logger.warning(f"{csv_path}: files with no info: {missing}")
            n += clean_info(csv_path)
        print(f"dropped {n} stale csv rows")
        return 0  # console-script return values become exit codes

    if args.token is None or args.config is None:
        raise SystemExit("--token and --config are required for Freesound downloads")
    cfg = DownloadConfig.from_yaml(args.config)
    inquirer = FreesoundInquirer.from_token(args.token)
    n = download_freesound(cfg, inquirer, args.out, num_jobs=args.num_jobs)
    print(f"downloaded {n} files")
    return 0


if __name__ == "__main__":
    main()
