"""Input-lists CLI — build and persist the per-signal .npy path lists used
for training and for rsync-style dataset staging.

Mirrors reference ``dnn/data/lists_to_load.py:43-89`` (write txt lists
consumable by ``rsync --files-from``, reference exp/ex1/oar_train.sh:28-45).
"""
from __future__ import annotations

import argparse

import numpy as np

from disco_tpu.nn.data import get_input_lists, write_input_lists


def build_parser():
    """Build the ``disco-lists`` argument parser."""
    p = argparse.ArgumentParser(description="Write training input file lists")
    p.add_argument("--scene", nargs="+", default=["living"])
    p.add_argument("--noise", default="ssn")
    p.add_argument("--zsigs", "-zs", nargs="+", default=["zs_hat"])
    p.add_argument("--zfile", "-zf", default="oracle")
    p.add_argument("--n_files", "-n", type=int, default=11001)
    p.add_argument("--path_data", "-path", default="dataset/disco/")
    p.add_argument("--out", "-o", default="lists/", help="folder for the txt lists")
    p.add_argument("--seed", type=int, default=26)
    return p


def main(argv=None):
    """``disco-lists`` console entry point."""
    args = build_parser().parse_args(argv)
    lists = get_input_lists(
        args.path_data,
        rirs_to_get=range(1, args.n_files),
        scenes=args.scene,
        noise_to_get=args.noise,
        z_sigs=args.zsigs,
        z_file=args.zfile,
        rng=np.random.default_rng(args.seed),
    )
    write_input_lists(lists, args.out)
    print(f"wrote {len(lists)} lists ({len(lists[0])} entries each) to {args.out}")
    return lists


if __name__ == "__main__":
    main()
