"""``disco-scenes`` — the batched scenario factory CLI.

Subcommands:

* ``simulate`` — draw + simulate N scene batches and report throughput
  (the command-line twin of the ``bench.py`` ``scenes_per_s`` lane, with
  the fence/retrace accounting printed so the one-dispatch-per-batch
  property is inspectable by hand).
* ``stream`` — pull training batches from a :class:`~disco_tpu.scenes.
  stream.SceneStream` and report window counts/shapes (the dry-run of the
  flywheel feed; ``--ledger``/``--resume`` exercise the scene-batch
  resume units).
* ``dynamic`` — simulate one moving-source scene and report the boundary
  continuity statistics the scene-check gate bounds.

Jax loads lazily inside each subcommand (disco-lint DL005): ``--help``
never touches the chip claim.

No reference counterpart: the reference has no scenario-factory tooling
(SURVEY.md §0).
"""
from __future__ import annotations

import argparse
import json
import time


def build_parser():
    """Build the ``disco-scenes`` argument parser."""
    p = argparse.ArgumentParser(description="Batched on-device scenario factory")
    sub = p.add_subparsers(dest="cmd", required=True)

    sim = sub.add_parser("simulate", help="simulate scene batches, report throughput")
    sim.add_argument("--batches", type=int, default=2, help="scene batches to simulate")
    sim.add_argument("--scenes", "-B", type=int, default=8, help="scenes per batch")
    sim.add_argument("--duration", type=float, default=1.0, help="dry seconds per scene")
    sim.add_argument("--scenario", default="random",
                     choices=["random", "meeting", "living", "meetit"])
    sim.add_argument("--max_order", type=int, default=8, help="ISM reflection order")
    sim.add_argument("--seed", type=int, default=0)

    st = sub.add_parser("stream", help="dry-run the SceneStream training feed")
    st.add_argument("--batches", type=int, default=2, help="scene batches per epoch")
    st.add_argument("--scenes", "-B", type=int, default=4, help="scenes per batch")
    st.add_argument("--batch_size", type=int, default=8, help="training batch size")
    st.add_argument("--duration", type=float, default=0.5, help="dry seconds per scene")
    st.add_argument("--win_len", type=int, default=8, help="window length in frames")
    st.add_argument("--max_order", type=int, default=4, help="ISM reflection order")
    st.add_argument("--seed", type=int, default=0)
    st.add_argument("--ledger", default=None,
                    help="RunLedger path (arms per-scene-batch verified "
                         "resume: ledger-done batches are skipped)")

    dyn = sub.add_parser("dynamic", help="simulate one moving-source scene")
    dyn.add_argument("--segments", type=int, default=6, help="stationary segments")
    dyn.add_argument("--crossfade", type=int, default=512,
                     help="boundary crossfade in samples (0 = hard switch)")
    dyn.add_argument("--duration", type=float, default=1.0, help="dry seconds")
    dyn.add_argument("--max_order", type=int, default=6)
    dyn.add_argument("--seed", type=int, default=0)
    return p


def _cmd_simulate(args) -> dict:
    import numpy as np

    from disco_tpu.obs import accounting
    from disco_tpu.scenes.batched import draw_scene_batch, simulate_scene_batch

    rng = np.random.default_rng(args.seed)
    g0, f0 = accounting.device_get_count(), accounting.fence_count()
    t0 = time.perf_counter()
    n_scenes = 0
    for _ in range(args.batches):
        batch = draw_scene_batch(rng, args.scenes, scenario=args.scenario,
                                 duration_s=args.duration)
        simulate_scene_batch(batch, max_order=args.max_order)
        n_scenes += batch.n_scenes
    dt = time.perf_counter() - t0
    return {
        "cmd": "simulate",
        "n_batches": args.batches,
        "n_scenes": n_scenes,
        "scenes_per_s": n_scenes / dt if dt > 0 else None,
        "elapsed_s": dt,
        "device_get_batches": accounting.device_get_count() - g0,
        "fences": accounting.fence_count() - f0,
        "recompiles_scene_batch": accounting.recompile_count("scene_batch"),
    }


def _cmd_stream(args) -> dict:
    from disco_tpu.scenes.stream import SceneStream

    stream = SceneStream(seed=args.seed, scenes_per_batch=args.scenes,
                         batches_per_epoch=args.batches,
                         duration_s=args.duration, max_order=args.max_order,
                         win_len=args.win_len)
    n, shape = 0, None
    t0 = time.perf_counter()
    for x, y in stream.batches(args.batch_size, epoch=0, ledger=args.ledger):
        n += 1
        shape = (list(x.shape), list(y.shape))
    dt = time.perf_counter() - t0
    return {
        "cmd": "stream",
        "n_batches": n,
        "batch_shape": shape,
        "elapsed_s": dt,
        "geometry": stream.peek_geometry(),
    }


def _cmd_dynamic(args) -> dict:
    import numpy as np

    from disco_tpu.scenes.dynamic import (
        boundary_jumps,
        dynamic_scene_mixture,
        piecewise_trajectory,
    )

    rng = np.random.default_rng(args.seed)
    fs = 16000
    L = int(args.duration * fs)
    t = np.arange(L) / fs
    dry = np.sin(2 * np.pi * 440 * t).astype(np.float32)
    path = piecewise_trajectory([1.0, 1.0, 1.5], [3.0, 2.0, 1.5], args.segments)
    mics = np.asarray([[2.0, 1.5, 1.0], [2.2, 1.5, 1.0]], np.float32)
    out = dynamic_scene_mixture([4.0, 3.0, 2.5], path, mics, 0.3, dry,
                                crossfade=args.crossfade,
                                max_order=args.max_order, rir_len=2048)
    jumps = boundary_jumps(out["mixture"], args.segments)
    return {
        "cmd": "dynamic",
        "n_segments": args.segments,
        "crossfade": args.crossfade,
        "mixture_shape": list(out["mixture"].shape),
        "boundary_jump_max": float(jumps.max()) if jumps.size else 0.0,
        "mixture_rms": float(np.sqrt(np.mean(np.square(out["mixture"])))),
    }


def main(argv=None):
    """``disco-scenes`` console entry point."""
    args = build_parser().parse_args(argv)
    if args.cmd == "simulate":
        out = _cmd_simulate(args)
    elif args.cmd == "stream":
        out = _cmd_stream(args)
    else:
        out = _cmd_dynamic(args)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
