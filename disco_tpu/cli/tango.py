"""TANGO enhancement CLI — the flagship per-RIR entry point.

Mirrors reference ``speech_enhancement/tango.py:644-692`` (flags
--vad_type/--sav_dir/--rir/--scenario/--noise/--mask_z/--mods/--zsigs and
the 'None'-string convention).  Unlike the reference module — unimportable
as shipped due to ``heymann``/``ipdb`` imports (SURVEY.md §7) — this one
imports and runs."""
from __future__ import annotations

import argparse

from disco_tpu.cli.common import (
    add_fault_args,
    add_ledger_arg,
    add_obs_log_arg,
    add_preflight_arg,
    add_resume_arg,
    add_trace_dir_arg,
    none_str,
    obs_session,
    resolve_fault_spec,
    run_preflight,
    snr_value,
    solver_spec,
)
from disco_tpu.enhance.driver import enhance_rir

_POLICIES = ["None", "local", "distant", "compressed", "use_oracle_refs", "use_oracle_zs"]


def build_parser():
    """Build the ``disco-tango`` argument parser."""
    p = argparse.ArgumentParser(description="Two-step distributed GEVD-MWF (TANGO) enhancement")
    p.add_argument("--vad_type", "-vt", nargs=2, default=["irm1", "irm1"],
                   help="mask type per step: irm1/ibm1/iam/... (tango.py:189-225)")
    p.add_argument("--sav_dir", "-sd", default="tango", help="results subfolder")
    p.add_argument("--rir", type=int, default=None, help="RIR id of the sample to filter")
    p.add_argument("--rirs", "-r", nargs=2, type=int, default=None,
                   help="first RIR id and count: batched corpus mode (vmapped launches)")
    p.add_argument("--batch_size", type=int, default=16, help="clips per jitted launch in --rirs mode")
    p.add_argument("--scenario", "-scene", choices=["living", "meeting", "random"], default="living")
    p.add_argument("--noise", choices=["ssn", "it", "fs"], default="fs")
    p.add_argument("--mask_z", "-mz", choices=_POLICIES, default="local",
                   help="mask applied to the exchanged z's in step 2")
    p.add_argument("--mods", "-m", nargs=2, default=["None", "None"],
                   help="paths to trained CRNN checkpoints per step, or None for oracle")
    p.add_argument("--zsigs", "-zs", nargs="+", default=["zs_hat"])
    p.add_argument("--archi", choices=["crnn", "rnn"], default="crnn",
                   help="architecture of the checkpoints passed via --mods")
    p.add_argument("--dataset", default="dataset/disco/", help="corpus root")
    p.add_argument("--snr", nargs=2, type=snr_value, default=[0, 6])
    p.add_argument("--out_root", default=None, help="override results directory")
    p.add_argument("--streaming", action="store_true",
                   help="frame-recursive online pipeline (smoothed covariances)")
    p.add_argument("--bucket", type=int, default=None,
                   help="round clip lengths up to this many samples to cap "
                        "recompiles on ragged corpora (0 = off; ~2 dB boundary "
                        "effect; default: off for --rir, 8192 for --rirs)")
    p.add_argument("--config", default=None,
                   help="YAML config file (config.save_config layout); its "
                        "enhance.solver becomes the --solver default")
    p.add_argument("--solver", type=solver_spec, default=None,
                   help="rank-1 GEVD solver: 'eigh' (batched eigendecomposition; "
                        "bit-matches the reference semantics), "
                        "'power'/'power:N' (dominant-pair power iteration; "
                        "streaming mode needs ~power:96 for eigh-level quality), "
                        "'jacobi[:N]' or 'jacobi-pallas[:N]' (cyclic Jacobi, "
                        "size-adaptive sweeps; full eig, so it tracks eigh in "
                        "streaming mode too), or 'fused[:N]'/'fused-xla[:N]'/"
                        "'fused-pallas[:N]' (the whole cov->whiten->Jacobi->"
                        "filter solve as ONE VMEM-resident program, "
                        "ops/mwf_ops.py; 'fused' resolves per backend — "
                        "DISCO_TPU_MWF_IMPL env overrides).  Default: 'power' "
                        "offline / 'eigh' with --streaming (measured "
                        "on-device, round-3 solver_ab)")
    p.add_argument("--cov_impl", choices=["auto", "xla", "pallas"], default="auto",
                   help="masked-covariance stage: 'auto' (fused pallas kernel "
                        "on TPU, folded einsum elsewhere — DISCO_TPU_COV_IMPL "
                        "env overrides), 'xla' (folded einsum) or 'pallas' "
                        "(fused single-read kernel, ops/cov_ops.py)")
    p.add_argument("--stft_impl", choices=["auto", "xla", "pallas"], default="auto",
                   help="fused spec+magnitude STFT stage: 'auto' (fused pallas "
                        "kernel on TPU, XLA elsewhere — DISCO_TPU_STFT_IMPL "
                        "env overrides), 'xla' or 'pallas' "
                        "(ops/stft_ops.stft_with_mag)")
    p.add_argument("--chained", action="store_true",
                   help="run each clip (or each --rirs chunk) as ONE "
                        "dispatched program — STFT, oracle masks, both MWF "
                        "steps and the scoring ISTFTs chained in-program "
                        "(enhance.fused) with one batched readback.  Offline "
                        "oracle lane only (rejects --streaming/--mods/--mesh/"
                        "fault flags); the solver default becomes 'fused'; "
                        "outputs are parity-matched to the staged path at the "
                        "documented chained tolerance, not bit-identical "
                        "(doc/source/performance.rst)")
    p.add_argument("--precision", choices=["f32", "bf16"], default="f32",
                   help="compute lane of the fused STFT/covariance kernels: "
                        "'f32' (default) or 'bf16' (bf16 multiplies with f32 "
                        "accumulators — faster on MXU, gated by looser oracle "
                        "tolerances; see doc/source/performance.rst)")
    p.add_argument("--mesh", nargs=2, type=int, default=None, metavar=("BATCH", "NODE"),
                   help="--rirs mode only: run each chunk on a (BATCH, NODE) device "
                        "mesh (clips sharded over 'batch', nodes over 'node', "
                        "GSPMD-placed collectives); needs BATCH*NODE devices and "
                        "--batch_size divisible by BATCH")
    add_fault_args(p)
    add_ledger_arg(p, "clip", default_hint="<out_root or results>/"
                   "ledger_<scenario>_<sav_dir>_<noise>.jsonl")
    add_resume_arg(p, "clip")
    p.add_argument("--no-pipeline", action="store_true",
                   help="--rirs mode: disable the overlapped corpus engine "
                        "(disco_tpu.enhance.pipeline — background chunk "
                        "prefetch, donated device buffers, one batched "
                        "readback per chunk) and fall back to the strictly "
                        "sequential load→dispatch→score loop; outputs are "
                        "byte-identical either way (make perf-check)")
    p.add_argument("--compile-cache", default=None, metavar="DIR|off",
                   help="persistent XLA compilation cache directory "
                        "(disco_tpu.utils.compile_cache) so per-bucket "
                        "programs compile once across runs/resumes; 'off' "
                        "disables.  Default: $DISCO_TPU_COMPILE_CACHE, else "
                        "~/.cache/disco_tpu/xla_cache (off on the tunneled "
                        "attachment unless a directory is given)")
    add_preflight_arg(p, what="the run")
    add_obs_log_arg(p)
    add_trace_dir_arg(p)
    return p


def _load_model(path, archi: str = "crnn", n_ch: int = 1):
    if none_str(path) is None:
        return None
    import numpy as np

    from disco_tpu.nn.crnn import build_crnn, build_rnn
    from disco_tpu.nn.training import create_train_state, load_params_for_inference

    if archi == "crnn":
        model, tx = build_crnn(n_ch=n_ch)
        x0 = np.zeros((1, n_ch, 21, 257), "float32")
    else:
        model, tx = build_rnn(n_ch=n_ch)
        x0 = np.zeros((1, 21, n_ch * 257), "float32")
    state = create_train_state(model, tx, x0)
    state = load_params_for_inference(path, state)
    return (model, {"params": state.params, "batch_stats": state.batch_stats})


def resolve_solver(args):
    """Solver precedence: explicit --solver > YAML enhance.solver from
    --config (only when the key is literally present in the file) > None,
    deferring to the driver's mode-aware default ('power' offline / 'eigh'
    streaming — enhance/driver.py, traceable to the round-3 solver_ab
    artifact).  The raw YAML is inspected rather than the default-filled
    EnhanceConfig: reading the dataclass field would silently turn "no
    solver in the file" into an explicit 'power', overriding the streaming
    default the help text promises."""
    if args.solver is not None:
        return args.solver
    if not args.config:
        return None
    import argparse as _argparse

    import yaml

    from disco_tpu.config import EnhanceConfig, config_from_dict

    # Parse ONCE: the same dict is both validated (config_from_dict) and
    # inspected for literal key presence, so the two views can never
    # diverge.  A present-but-empty section ('enhance:\n') parses as None;
    # normalize it to {} so validation sees "section with all defaults".
    with open(args.config) as fh:
        raw = yaml.safe_load(fh) or {}
    if not isinstance(raw, dict):
        # a YAML list/scalar top level would crash .items() below with a raw
        # AttributeError (round-5 advisor finding) — clean error instead
        raise SystemExit(
            f"--config {args.config}: expected a mapping of config sections "
            f"at the top level, got {type(raw).__name__}"
        )
    raw = {k: ({} if v is None and k != "root" else v) for k, v in raw.items()}
    raw_enh = raw.get("enhance", {})
    if not isinstance(raw_enh, dict):
        # 'enhance: eigh' — a scalar section would otherwise surface as an
        # uncaught ValueError deep inside config_from_dict
        raise SystemExit(
            f"--config {args.config}: 'enhance' must be a mapping of fields "
            f"(e.g. 'enhance:\\n  solver: eigh'), got {raw_enh!r}"
        )
    cfg_enh = config_from_dict(raw).enhance  # full validation of the file
    # Only enhance.solver is consumed here; silently honoring part of a
    # DiscoConfig YAML would be a trap, so name what is being ignored.
    import dataclasses
    import sys

    ignored = [
        f.name
        for f in dataclasses.fields(EnhanceConfig)
        if f.name != "solver"
        and getattr(cfg_enh, f.name) != getattr(EnhanceConfig(), f.name)
    ]
    if ignored:
        print(
            f"warning: --config {args.config}: only enhance.solver is used by "
            f"this CLI; ignoring non-default enhance fields {ignored}",
            file=sys.stderr,
        )
    if "solver" not in raw_enh:
        return None
    raw_solver = raw_enh["solver"]
    if not isinstance(raw_solver, str):
        # 'solver: null' / 'solver: 5' — clean error, not an AttributeError
        # from str.partition deep inside the spec parser.
        raise SystemExit(
            f"--config {args.config}: enhance.solver: expected a solver spec "
            f"string ('eigh' | 'power[:N]' | 'jacobi[:N]' | ...), got {raw_solver!r}"
        )
    try:
        return solver_spec(raw_solver)
    except _argparse.ArgumentTypeError as e:
        raise SystemExit(f"--config {args.config}: enhance.solver: {e}")


def resolve_ledger(args):
    """--ledger / --resume resolution: an explicit path wins; --resume
    without a path lands at a deterministic default under the results root
    so interrupted-then-resumed invocations agree on the file."""
    if args.ledger is None and not args.resume:
        return None
    if args.ledger is not None:
        return args.ledger
    from pathlib import Path

    return str(
        Path(args.out_root or "results")
        / f"ledger_{args.scenario}_{args.sav_dir}_{args.noise}.jsonl"
    )


def main(argv=None):
    """``disco-tango`` console entry point."""
    args = build_parser().parse_args(argv)
    args.solver = resolve_solver(args)
    if args.rir is None and args.rirs is None:
        raise SystemExit("one of --rir or --rirs is required")
    if args.mesh is not None and args.rirs is None:
        raise SystemExit("--mesh needs batched corpus mode (--rirs)")
    args.fault_spec = resolve_fault_spec(args)
    args.ledger = resolve_ledger(args)
    policy = none_str(args.mask_z) or "none"

    with obs_session(args, tool="disco-tango"):
        preflight = run_preflight(args)
        from disco_tpu import obs as _obs

        _obs.record("run_start", stage="enhance", tool="disco-tango",
                    preflight=preflight, ledger=args.ledger, resume=args.resume)
        from disco_tpu.runs import GracefulInterrupt

        with GracefulInterrupt() as stopped:
            out = _run(args, policy)
        if stopped():
            print("interrupted — run is resumable: rerun with --resume "
                  f"{'--ledger ' + args.ledger if args.ledger else ''}".rstrip())
        return out


def _run(args, policy):
    import contextlib

    from disco_tpu.utils import trace_to

    trace_cm = trace_to(args.trace_dir) if args.trace_dir else contextlib.nullcontext()
    compile_cache = (False if args.compile_cache in ("off", "0")
                     else args.compile_cache)
    # step-2 model consumes [y_ref ‖ z exchanges]: 1 + (K-1)*len(zsigs)
    # channels (reference nodes_nbs, tango.py:492-494)
    n_ch2 = 1 + 3 * len(args.zsigs)
    models = (
        _load_model(args.mods[0], archi=args.archi),
        _load_model(args.mods[1], archi=args.archi, n_ch=n_ch2),
    )
    if args.rirs is not None:
        if args.streaming:
            raise SystemExit("--streaming needs per-RIR mode (--rir)")
        from disco_tpu.enhance.driver import enhance_rirs_batched

        mesh = None
        if args.mesh is not None:
            import jax

            from disco_tpu.parallel import make_mesh

            n_batch, n_node = args.mesh
            n_dev = len(jax.devices())
            if n_batch * n_node > n_dev:
                raise SystemExit(
                    f"--mesh {n_batch} {n_node} needs {n_batch * n_node} devices; "
                    f"{n_dev} available"
                )
            if args.batch_size % n_batch:
                raise SystemExit(
                    f"--batch_size {args.batch_size} must be divisible by the mesh "
                    f"batch axis ({n_batch})"
                )
            if 4 % n_node:  # the DISCO array has 4 nodes (tango.py:30)
                raise SystemExit(f"the 4-node array is not divisible over {n_node} mesh nodes")
            mesh = make_mesh(n_batch=n_batch, n_node=n_node)
        with trace_cm:
            results = enhance_rirs_batched(
                args.dataset, args.scenario, range(args.rirs[0], args.rirs[0] + args.rirs[1]),
                args.noise, save_dir=args.sav_dir, snr_range=tuple(args.snr),
                mask_type=args.vad_type[0], policy=policy, out_root=args.out_root,
                bucket=8192 if args.bucket is None else args.bucket,
                max_batch=args.batch_size, models=models,
                z_sigs=args.zsigs[0] if len(args.zsigs) == 1 else "zs&zn",
                solver=args.solver, cov_impl=args.cov_impl,
                stft_impl=args.stft_impl, precision=args.precision, mesh=mesh,
                chained=args.chained,
                fault_spec=args.fault_spec,
                ledger=args.ledger, resume=args.resume,
                pipeline=not args.no_pipeline,
                compile_cache=compile_cache,
            )
        print(f"{len(results)} RIRs enhanced (batched)")
        return results
    # --compile-cache applies to BOTH modes: the per-RIR path pays the same
    # per-shape compile tax (stft/tango/istft programs) on every invocation
    from disco_tpu.utils import compile_cache as _compile_cache

    _compile_cache.ensure_enabled(compile_cache)
    with trace_cm:
        results = enhance_rir(
            args.dataset, args.scenario, args.rir, args.noise,
            save_dir=args.sav_dir, snr_range=tuple(args.snr),
            mask_type=args.vad_type[0], policy=policy, models=models,
            out_root=args.out_root, streaming=args.streaming, bucket=args.bucket or 0,
            z_sigs=args.zsigs[0] if len(args.zsigs) == 1 else "zs&zn",
            solver=args.solver, cov_impl=args.cov_impl,
            stft_impl=args.stft_impl, precision=args.precision,
            chained=args.chained,
            fault_spec=args.fault_spec, ledger=args.ledger,
        )
    if results is None:
        print(f"Conf {args.rir} with {args.noise} noise already processed")
    else:
        print(f"{args.rir} done")
    return results


if __name__ == "__main__":
    main()
