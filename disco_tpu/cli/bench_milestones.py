"""Milestone benchmark CLI: run the BASELINE.json configurations 1-5 plus
the streaming-latency config 6 (`disco_tpu.milestones`), and optionally the
self-generated-corpus pipeline, printing one JSON line per config.

No reference counterpart: the reference repo ships no benchmark CLI.
"""
from __future__ import annotations

import argparse
import json

from disco_tpu import milestones


def build_parser():
    """Build the ``disco-milestones`` argument parser."""
    p = argparse.ArgumentParser(description="Run the BASELINE milestone benchmark configs")
    p.add_argument("--tiny", action="store_true", help="small CPU-testable scales")
    p.add_argument("--configs", nargs="+", type=int, default=None,
                   help="subset of configs to run (1-6; 6 = streaming latency)")
    p.add_argument("--corpus", action="store_true",
                   help="also run the self-generated-corpus pipeline milestone "
                        "(gen→mix→train→tango, disco_tpu.milestones_corpus)")
    p.add_argument("--workdir", default=None, help="corpus milestone working dir")
    return p


def main(argv=None):
    """``disco-milestones`` console entry point."""
    args = build_parser().parse_args(argv)
    if args.corpus:
        import tempfile

        from disco_tpu.milestones_corpus import corpus_milestone

        workdir = args.workdir or tempfile.mkdtemp(prefix="disco_corpus_milestone_")
        kwargs = dict(n_rirs=2, n_epochs=2, max_order=6) if args.tiny else {}
        res = corpus_milestone(workdir, **kwargs)
        print(json.dumps(res))  # then the standard configs run as usual
    fns = {
        1: milestones.mvdr_single_clip,
        2: milestones.disco_mwf_4node,
        3: milestones.tango_4node,
        4: milestones.meetit_separation,
        5: milestones.batched_meetit_end_to_end,
        6: milestones.streaming_latency,
    }
    if args.configs is None and args.tiny:
        results = milestones.run_all(tiny=True)
    else:
        ids = args.configs or sorted(fns)
        tiny_kwargs = {
            1: dict(dur_s=1.0, iters=1),
            2: dict(dur_s=1.0, iters=1),
            3: dict(dur_s=1.0, iters=1),
            4: dict(dur_s=1.0, K=4, C=2, iters=1),
            5: dict(n_rooms=2, K=2, C=2, dur_s=0.5, max_order=4, rir_len=1024, iters=1),
            6: dict(dur_s=1.0, K=2, C=2, iters=1),
        }
        results = [fns[i](**(tiny_kwargs[i] if args.tiny else {})) for i in ids]
    for res in results:
        print(json.dumps(res))
    return results


if __name__ == "__main__":
    main()
