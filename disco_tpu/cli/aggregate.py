"""Aggregate per-RIR OIM result pickles into summary statistics.

The reference pickles ~20 metrics per RIR (tango.py:617-635) and leaves
cross-RIR aggregation entirely to the user, providing only the ``ci_wp``
helper (metrics.py:283) and ``bar_data`` (misc_utils.py:102).  This CLI is
that missing last step: mean ± 95% CI per metric over every RIR in a
results tree, as a table or one JSON line — the numbers that become a
paper table row.
"""
from __future__ import annotations

import argparse
import json

import numpy as np


def build_parser():
    """Build the ``disco-aggregate`` argument parser."""
    p = argparse.ArgumentParser(description="Aggregate per-RIR OIM pickles: mean ± 95% CI per metric")
    p.add_argument("oim_dir", help="OIM directory of a results tree (…/{save_dir}/OIM)")
    p.add_argument("--kind", choices=["tango", "mwf"], default="tango",
                   help="which pickle family to aggregate")
    p.add_argument("--noise", default=None, help="restrict to one noise condition")
    p.add_argument("--keys", nargs="+", default=None, help="subset of metric keys")
    p.add_argument("--json", action="store_true", help="print one JSON line instead of a table")
    return p


def summarize(agg: dict, keys=None) -> dict:
    """{key: {mean, ci95, n}} over the stacked per-RIR arrays, NaN-robust
    (the reference's STOI can be NaN on too-short segments)."""
    from disco_tpu.core.metrics import ci_wp

    out = {}
    for key in keys or sorted(agg):
        v = np.asarray(agg[key], np.float64).ravel()
        v = v[np.isfinite(v)]
        if v.size == 0:
            out[key] = {"mean": float("nan"), "ci95": float("nan"), "n": 0}
            continue
        out[key] = {"mean": float(np.mean(v)), "ci95": float(ci_wp(v)), "n": int(v.size)}
    return out


def main(argv=None):
    """``disco-aggregate`` console entry point."""
    args = build_parser().parse_args(argv)

    from disco_tpu.enhance.driver import aggregate_results

    agg = aggregate_results(args.oim_dir, kind=args.kind, noise=args.noise)
    if not agg:
        print(f"no results_{args.kind}_* pickles under {args.oim_dir}")
        return {}
    summary = summarize(agg, keys=args.keys)
    if args.json:
        print(json.dumps(summary))
    else:
        width = max(len(k) for k in summary)
        print(f"{'metric':<{width}}  {'mean':>9}  {'±95% CI':>9}  {'n':>5}")
        for key, s in summary.items():
            print(f"{key:<{width}}  {s['mean']:>9.3f}  {s['ci95']:>9.3f}  {s['n']:>5}")
    return summary


if __name__ == "__main__":
    main()
