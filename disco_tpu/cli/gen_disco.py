"""DISCO dataset generation CLI — rooms, RIRs, convolved sources.

Mirrors reference ``dataset_generation/gen_disco/convolve_signals.py:329-448``
(flags --dset/--scenario/--rirs/--dir_out; the reference's ``args.rir_id``
flag-mismatch bug is not reproduced, SURVEY.md §7)."""
from __future__ import annotations

import argparse

import numpy as np

from disco_tpu.cli.common import (
    add_ledger_arg,
    add_resume_arg,
    add_rirs_arg,
    add_scenario_arg,
)
from disco_tpu.datagen.disco import generate_disco_rirs, get_wavs_list
from disco_tpu.io.layout import DatasetLayout
from disco_tpu.sim.signals import SpeechAndNoiseSetup


def build_parser():
    """Build the ``disco-gen`` argument parser."""
    p = argparse.ArgumentParser(description="Generate DISCO rooms + convolved signals")
    p.add_argument("--dset", choices=["train", "test"], default="test")
    add_scenario_arg(p)
    add_rirs_arg(p)
    p.add_argument("--dir_out", "-d", default="dataset/disco/", help="corpus root")
    p.add_argument("--librispeech", default="dataset/LibriSpeech/", help="LibriSpeech root")
    p.add_argument("--freesound", default=None, help="Freesound noise wav directory")
    p.add_argument("--max_order", type=int, default=20, help="ISM reflection order")
    p.add_argument("--duration", nargs=2, type=float, default=[5, 10],
                   help="min/max clip duration in seconds (convolve_signals.py:404)")
    p.add_argument("--seed", type=int, default=30, help="global seed (convolve_signals.py:330)")
    p.add_argument("--batched", action="store_true",
                   help="batched scenario factory: one RIR-engine dispatch "
                        "per --batch scenes (disco_tpu.scenes) instead of "
                        "one per scene")
    p.add_argument("--batch", type=int, default=8,
                   help="scenes per batched dispatch (with --batched)")
    add_ledger_arg(p, "scene",
                   default_hint="<dir_out>/log/ledger_<scenario>_<dset>.jsonl")
    add_resume_arg(p, "scene", regen="regenerated")
    return p


def main(argv=None):
    """``disco-gen`` console entry point."""
    args = build_parser().parse_args(argv)
    rir_start, n_rirs = args.rirs
    if args.ledger is None and args.resume:
        args.ledger = f"{args.dir_out}/log/ledger_{args.scenario}_{args.dset}.jsonl"
    rng = np.random.default_rng(args.seed)
    targets, talkers, noises = get_wavs_list(
        args.librispeech, args.freesound, dset=args.dset, cache_dir=f"{args.dir_out}/log/lists"
    )
    if not targets:
        raise SystemExit(f"no speech files found under {args.librispeech}")
    signal_setup = SpeechAndNoiseSetup(
        target_list=targets,
        talkers_list=talkers,
        noises_dict=noises,
        duration_range=tuple(args.duration),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-10, 15),
        min_delta_snr=0.0,
        rng=rng,
    )
    layout = DatasetLayout(args.dir_out, args.scenario, args.dset)
    from disco_tpu.runs import GracefulInterrupt

    with GracefulInterrupt() as stopped:
        if args.batched:
            from disco_tpu.datagen.disco import generate_disco_rirs_batched

            done = generate_disco_rirs_batched(
                args.scenario, args.dset, rir_start, n_rirs, signal_setup,
                layout, rng=rng, max_order=args.max_order,
                ledger=args.ledger, resume=args.resume, batch=args.batch,
                seed=args.seed,
            )
        else:
            done = generate_disco_rirs(
                args.scenario, args.dset, rir_start, n_rirs, signal_setup, layout,
                rng=rng, max_order=args.max_order,
                ledger=args.ledger, resume=args.resume,
            )
    if stopped():
        print("interrupted — generation is resumable: rerun the same command "
              "(idempotent; add --resume for digest-verified skips)")
    print(f"generated {len(done)} RIRs: {done}")
    return done


if __name__ == "__main__":
    main()
