"""Shared CLI conventions of the reference's argparse entry points:
the ``'None'``-string -> None convention (tango.py:682-688, train.py:63-65)
and the ``--rirs start count`` pair every corpus-scale CLI takes for
embarrassingly-parallel job arrays (SURVEY.md §2.9 DP row).

Also THE home of the production seams every long-running CLI shares —
``--obs-log`` / ``--ledger`` / ``--resume`` / ``--preflight`` /
``--fault-spec`` argparse declarations and their wiring
(:func:`obs_session`, :func:`run_preflight`, :func:`resolve_fault_spec`) —
factored out of ``disco-tango`` / ``disco-train`` / ``disco-gen`` so a new
entry point (``disco-serve``) gets the whole story by adding five lines,
and a fix to any seam lands in every CLI at once.  No reference
counterpart: the reference CLIs have no telemetry, resume or health-probe
story at all (SURVEY.md §5.1, §7)."""
from __future__ import annotations

import contextlib


def none_str(v):
    """argparse type honoring the reference's 'None' string convention."""
    return None if v in (None, "None", "none") else v


def add_rirs_arg(parser, default=(1, 1)):
    """Attach the shared ``--rirs`` range argument."""
    parser.add_argument(
        "--rirs", "-r", nargs=2, type=int, default=list(default),
        help="First RIR id and number of RIRs to process (job-array sharding)",
    )


def add_scenario_arg(parser, default="random", choices=("random", "living", "meeting")):
    """Attach the shared ``--scenario`` argument."""
    parser.add_argument(
        "--scenario", "-s", type=str, choices=list(choices), default=default,
        help="Spatial configuration",
    )


def add_noise_arg(parser, default="ssn", choices=("ssn", "fs", "it")):
    """Attach the shared ``--noise`` argument."""
    parser.add_argument("--noise", "-n", type=str, choices=list(choices), default=default)


def snr_value(v: str):
    """SNR bound argparse type: int when integral so snr directory names
    match the reference's '0-6' convention (post_generator.py:66-68)."""
    f = float(v)
    return int(f) if f == int(f) else f


def solver_spec(v: str):
    """argparse type for rank-1 GEVD solver specs — delegates to THE shared
    grammar (``disco_tpu.solver_spec.parse_solver_spec``, stdlib-only so
    rejecting a typo costs no jax import): 'eigh', 'power[:N]',
    'jacobi[:N]', 'jacobi-pallas[:N]' or the fused solve family
    'fused[:N]' / 'fused-xla[:N]' / 'fused-pallas[:N]'."""
    import argparse

    from disco_tpu.solver_spec import parse_solver_spec

    try:
        parse_solver_spec(v)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))
    return v


# -- the shared production seams (obs / ledger / preflight / faults) ---------
def add_obs_log_arg(parser, what: str = "run") -> None:
    """Attach the shared ``--obs-log`` telemetry arguments."""
    parser.add_argument(
        "--obs-log", default=None,
        help=f"record structured {what} telemetry (manifest, per-stage "
             "events, fence/RPC accounting, counters) to this JSONL file; "
             "render with `python -m disco_tpu.cli.obs report PATH`",
    )
    parser.add_argument(
        "--obs-log-max-bytes", type=int, default=None, metavar="N",
        help="rotate the --obs-log file once it exceeds N bytes "
             "(events.jsonl -> events.1.jsonl, ...; `disco-obs report` "
             "spans the segments transparently) — bounds the log of a "
             "week-long serve/soak run; default: no rotation",
    )


def add_trace_dir_arg(parser) -> None:
    """Attach the shared ``--trace-dir`` profiling argument."""
    parser.add_argument(
        "--trace-dir", default=None,
        help="capture a jax.profiler trace into this directory (view with "
             "XProf/TensorBoard; no-op if the profiler is unavailable)",
    )


def add_preflight_arg(parser, what: str = "the run") -> None:
    """Attach the shared ``--preflight`` device-probe flag."""
    parser.add_argument(
        "--preflight", type=float, default=0.0, metavar="SECONDS",
        help="run a bounded-deadline device health probe (one tiny fenced "
             "dispatch, utils.resilience.preflight_probe) before "
             f"{what} claims the chip; fail fast with a clean error if the "
             "attachment is wedged (0 = off)",
    )


def add_ledger_arg(parser, unit: str, default_hint: str | None = None) -> None:
    """``--ledger``: the run-ledger JSONL path; ``unit`` names the work unit
    the records track ('clip', 'epoch', 'scene', ...)."""
    parser.add_argument(
        "--ledger", default=None,
        help=f"run-ledger JSONL path (disco_tpu.runs.ledger): record "
             f"per-{unit} state + artifact digests for verified resume"
             + (f".  Default when --resume is set: {default_hint}" if default_hint else ""),
    )


def add_resume_arg(parser, unit: str = "unit", regen: str = "requeued") -> None:
    """Attach the shared ``--resume`` flag (pairs with ``--ledger``)."""
    parser.add_argument(
        "--resume", action="store_true",
        help=f"resume from the ledger: done {unit}s are VERIFIED against "
             f"their artifact digests and skipped; corrupt/missing ones are "
             f"{regen} (truncated files are never trusted).  Graceful "
             "SIGTERM/SIGINT during a run exits resumable with this flag",
    )


def add_fault_args(parser) -> None:
    """Attach the shared ``--fault-spec``/``--fault-seed`` arguments."""
    parser.add_argument(
        "--fault-spec", default=None,
        help="YAML/JSON fault scenario (disco_tpu.fault.FaultSpec fields: "
             "node_dropout, dropout_prob, link_loss_prob, stale_prob, "
             "nan_z, nan_prob, seed): inject seeded faults at the "
             "z-exchange and run degraded-mode beamforming; every fault "
             "lands in the obs event log (doc/source/robustness.rst)",
    )
    parser.add_argument(
        "--fault-seed", type=int, default=None,
        help="override the fault spec's seed (ablation sweeps over fault "
             "realizations without editing the file)",
    )


def add_tap_args(parser) -> None:
    """Attach the shared flywheel corpus-tap arguments (``disco-serve``)."""
    parser.add_argument(
        "--tap-dir", default=None,
        help="opt-in flywheel corpus tap (disco_tpu.flywheel): spool every "
             "delivered block's (noisy, enhanced, mask) tuple into rotating "
             "training shards under this directory on a host-only "
             "background thread; overflow drops-and-counts (tap_dropped) — "
             "serving never backpressures on the tap.  Train on the shards "
             "with `disco-train --shards DIR`",
    )
    parser.add_argument(
        "--tap-records-per-shard", type=int, default=64,
        help="blocks per rotated shard file (atomic write + sha256 "
             "manifest record each rotation)",
    )
    parser.add_argument(
        "--tap-queue-blocks", type=int, default=256,
        help="bound on spooled-but-unwritten tap blocks; offers past it "
             "are dropped and counted, never queued unboundedly",
    )


def resolve_tap(args):
    """Build the :class:`~disco_tpu.flywheel.CorpusTap` described by the
    ``--tap-*`` arguments (None without ``--tap-dir``).  The caller owns the
    tap's lifecycle and must ``close()`` it after the server drains."""
    if getattr(args, "tap_dir", None) is None:
        return None
    from disco_tpu.flywheel import CorpusTap

    return CorpusTap(
        args.tap_dir,
        max_queue_blocks=args.tap_queue_blocks,
        records_per_shard=args.tap_records_per_shard,
    )


def resolve_fault_spec(args):
    """Load ``--fault-spec`` (with the optional ``--fault-seed`` override)
    into a FaultSpec, converting file/format errors into clean CLI errors."""
    if args.fault_spec is None:
        if args.fault_seed is not None:
            raise SystemExit("--fault-seed needs --fault-spec")
        return None
    import dataclasses

    from disco_tpu.fault import load_fault_spec

    try:
        spec = load_fault_spec(args.fault_spec)
    except (OSError, ValueError) as e:
        raise SystemExit(f"--fault-spec {args.fault_spec}: {e}")
    if args.fault_seed is not None:
        spec = dataclasses.replace(spec, seed=args.fault_seed)
    return spec


def run_preflight(args):
    """Execute the ``--preflight`` probe (no-op at the 0.0 default).
    Returns the probe's result dict (it rides the ``run_start`` event), or
    exits with a clean error naming the failure — never a raw traceback."""
    if not getattr(args, "preflight", 0):
        return None
    from disco_tpu.utils.resilience import PreflightFailed, preflight_probe

    try:
        return preflight_probe(deadline_s=args.preflight)
    except PreflightFailed as e:
        raise SystemExit(f"preflight: {e}")


@contextlib.contextmanager
def obs_session(args, tool: str):
    """The ``--obs-log`` wiring every production CLI shares: enable the
    recorder and write the run manifest (the full non-None arg vector as
    config) on entry; flush a final counters snapshot and release the
    recorder on exit, crash or not.  No-op without ``--obs-log``."""
    obs_log = getattr(args, "obs_log", None)
    if obs_log:
        from disco_tpu import obs

        obs.enable(obs_log,
                   max_bytes=getattr(args, "obs_log_max_bytes", None))
        obs.write_manifest(
            config={k: v for k, v in vars(args).items() if v is not None},
            tool=tool,
        )
    try:
        yield
    finally:
        if obs_log:
            from disco_tpu import obs

            obs.record("counters", **obs.REGISTRY.snapshot())
            obs.disable()
