"""Shared CLI conventions of the reference's argparse entry points:
the ``'None'``-string -> None convention (tango.py:682-688, train.py:63-65)
and the ``--rirs start count`` pair every corpus-scale CLI takes for
embarrassingly-parallel job arrays (SURVEY.md §2.9 DP row)."""
from __future__ import annotations


def none_str(v):
    """argparse type honoring the reference's 'None' string convention."""
    return None if v in (None, "None", "none") else v


def add_rirs_arg(parser, default=(1, 1)):
    parser.add_argument(
        "--rirs", "-r", nargs=2, type=int, default=list(default),
        help="First RIR id and number of RIRs to process (job-array sharding)",
    )


def add_scenario_arg(parser, default="random", choices=("random", "living", "meeting")):
    parser.add_argument(
        "--scenario", "-s", type=str, choices=list(choices), default=default,
        help="Spatial configuration",
    )


def add_noise_arg(parser, default="ssn", choices=("ssn", "fs", "it")):
    parser.add_argument("--noise", "-n", type=str, choices=list(choices), default=default)


def snr_value(v: str):
    """SNR bound argparse type: int when integral so snr directory names
    match the reference's '0-6' convention (post_generator.py:66-68)."""
    f = float(v)
    return int(f) if f == int(f) else f


def solver_spec(v: str):
    """argparse type for rank-1 GEVD solver specs — delegates to THE shared
    grammar (``disco_tpu.beam.filters.parse_solver_spec``): 'eigh',
    'power[:N]', 'jacobi[:N]' or 'jacobi-pallas[:N]'."""
    import argparse

    from disco_tpu.beam.filters import parse_solver_spec

    try:
        parse_solver_spec(v)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))
    return v
