"""MEETIT dataset generation CLI — N interfering speakers around a table.

Mirrors reference ``dataset_generation/gen_meetit/convolve_signals.py:210-302``
(flags --dset/--rirs/--n_src/--dir_out; the start-time stagger sleep is not
needed — idempotency guards make parallel shards collision-free)."""
from __future__ import annotations

import argparse

import numpy as np

from disco_tpu.cli.common import add_rirs_arg
from disco_tpu.datagen.disco import get_wavs_list
from disco_tpu.datagen.meetit import generate_meetit_rirs
from disco_tpu.io.layout import DatasetLayout
from disco_tpu.sim.signals import InterferentSpeakersSetup


def build_parser():
    """Build the ``disco-gen-meetit`` argument parser."""
    p = argparse.ArgumentParser(description="Generate MEETIT meeting-room mixtures")
    p.add_argument("--dset", choices=["train", "val", "test"], default="test")
    add_rirs_arg(p)
    p.add_argument("--n_src", "-n", type=int, default=2, help="number of interfering speakers (= nodes)")
    p.add_argument("--dir_out", "-do", default="dataset/meetit/", help="corpus root")
    p.add_argument("--librispeech", default="dataset/LibriSpeech/", help="LibriSpeech root")
    p.add_argument("--max_order", type=int, default=20)
    p.add_argument("--duration", nargs=2, type=float, default=[5, 10],
                   help="min/max clip duration in seconds (convolve_signals.py:404)")
    p.add_argument("--seed", type=int, default=30)
    return p


def main(argv=None):
    """``disco-gen-meetit`` console entry point."""
    args = build_parser().parse_args(argv)
    rir_start, n_rirs = args.rirs
    rng = np.random.default_rng(args.seed + rir_start)
    targets, _talkers, _ = get_wavs_list(
        args.librispeech, None, dset=args.dset, cache_dir=f"{args.dir_out}/log/lists"
    )
    if not targets:
        raise SystemExit(f"no speech files found under {args.librispeech}")
    signal_setup = InterferentSpeakersSetup(
        speakers_list=targets,
        duration_range=tuple(args.duration),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-10, 15),
        min_delta_snr=0.0,
        rng=rng,
    )
    layout = DatasetLayout(args.dir_out, "meetit", args.dset)
    done = generate_meetit_rirs(
        args.n_src, args.dset, rir_start, n_rirs, signal_setup, layout,
        rng=rng, max_order=args.max_order,
    )
    print(f"generated {len(done)} RIRs: {done}")
    return done


if __name__ == "__main__":
    main()
