"""``disco-serve`` — the online enhancement service CLI.

Binds the continuous-batching enhancement server (:mod:`disco_tpu.serve`)
on a TCP or unix socket and serves streaming sessions until interrupted.
The production seams are the shared ones from :mod:`disco_tpu.cli.common`:

* ``--preflight`` probes the device attachment before the server claims
  the chip for its whole lifetime (a wedged tunnel fails in seconds, not
  after clients connect);
* the first SIGINT/SIGTERM triggers a graceful drain
  (:class:`~disco_tpu.runs.interrupt.GracefulInterrupt`): admission stops,
  every queued block is enhanced and delivered, live sessions are
  checkpointed under ``--state-dir`` (atomic msgpack + digest) and closed
  with their resume coordinates — zero truncated or lost frames;
* ``--obs-log`` records the session lifecycle, the
  ``sessions_active``/``queue_depth``/``batch_occupancy`` gauges,
  ``admission_reject``/``session_evicted`` counters and the
  ``serve_block_latency_ms`` histogram, rendered with percentiles by
  ``disco-obs report``;
* ``--fault-spec`` expands a per-session seeded fault plan at admission
  (``disco_tpu.fault``) — degraded-mode beamforming flows through the
  service unchanged;
* ``--tap-dir`` arms the flywheel corpus tap (``disco_tpu.flywheel``):
  every delivered block's (noisy, enhanced, mask) tuple is spooled into
  rotating training shards on a host-only background thread — overflow
  drops-and-counts, serving never backpressures; train on the shards
  with ``disco-train --shards``.
* ``--train`` closes the loop inside ONE process: the co-resident trainer
  (``disco_tpu.flywheel.resident``) consumes the ``--tap-dir`` shards as
  bounded train-step slices interleaved on the dispatch thread, publishes
  generations into ``--promote-dir`` on a cadence, throttles under ladder
  distress and resumes from its ledger after any crash — the continuous
  serve→train→promote flywheel ``make endure-check`` drills.

No reference counterpart: the reference pipeline is strictly offline
(SURVEY.md §2); this is the ROADMAP's "serves heavy traffic" entry point.
"""
from __future__ import annotations

import argparse

from disco_tpu.cli.common import (
    add_fault_args,
    add_obs_log_arg,
    add_preflight_arg,
    add_tap_args,
    obs_session,
    resolve_fault_spec,
    resolve_tap,
    run_preflight,
)


def build_parser():
    """Build the ``disco-serve`` argument parser."""
    p = argparse.ArgumentParser(
        description="Online TANGO enhancement service: continuous batching "
                    "of concurrent streaming sessions on one device"
    )
    p.add_argument("--host", default="127.0.0.1",
                   help="TCP bind address (loopback by default; the protocol "
                        "is unauthenticated)")
    p.add_argument("--port", type=int, default=7433,
                   help="TCP port (0 = ephemeral, printed at startup)")
    p.add_argument("--unix", default=None, metavar="PATH",
                   help="bind a unix domain socket at PATH instead of TCP")
    p.add_argument("--max-sessions", type=int, default=16,
                   help="admission bound on concurrently live sessions; "
                        "opens past it get a clean 'capacity' error frame")
    p.add_argument("--max-queue-blocks", type=int, default=8,
                   help="per-session input-queue bound (backpressure error "
                        "frames instead of unbounded host memory)")
    p.add_argument("--max-backlog", type=int, default=64,
                   help="per-connection output-frame bound: a client that "
                        "stops reading its socket is evicted once this many "
                        "enhanced frames are backed up")
    p.add_argument("--max-blocks-per-tick", type=int, default=64,
                   help="blocks enhanced per scheduler tick across all "
                        "sessions (bounds one tick's device queue and its "
                        "single batched readback)")
    p.add_argument("--blocks-per-super-tick", type=int, default=1,
                   help="N: dispatch each run of N consecutive full queued "
                        "blocks of a session as ONE scanned on-device "
                        "program (streaming_tango_scan), amortizing the "
                        "fixed ~80 ms tunnel RPC per fenced readback across "
                        "N blocks; sub-N remainders (and ragged final "
                        "blocks) fall back to the per-block path.  Raises "
                        "per-block latency by up to N-1 blocks of admission "
                        "wait in exchange for ~N× dispatch throughput — "
                        "results stay bit-exact either way (1 = per-block "
                        "serving, the default; must be <= "
                        "--max-blocks-per-tick)")
    p.add_argument("--no-chained-sessions", dest="allow_chained",
                   action="store_false", default=True,
                   help="do not admit chained (domain='time') sessions — "
                        "clients that stream raw audio windows through the "
                        "one-program chained twin "
                        "(enhance.fused.streaming_clip_fused, one fenced "
                        "dispatch per window).  Each chained shape bucket "
                        "compiles its own program; this restores the "
                        "bounded STFT-only compile surface")
    p.add_argument("--no-overlap-readback", dest="overlap_readback",
                   action="store_false", default=None,
                   help="disable the double-buffered tick state (with "
                        "super-ticks, tick T+1's dispatch normally overlaps "
                        "tick T's batched readback; this forces read-after-"
                        "dispatch within each tick)")
    p.add_argument("--tick-interval", type=float, default=0.002,
                   metavar="SECONDS",
                   help="dispatch-thread sleep between idle ticks")
    p.add_argument("--state-dir", default=None,
                   help="directory for live-session checkpoints: a graceful "
                        "drain saves every open session here (atomic msgpack "
                        "+ sha256 digest) and a later server resumes them "
                        "(client opens with resume=<session id>); parked "
                        "sessions checkpoint here too, so a reattach "
                        "survives even a server death in between")
    p.add_argument("--park-ttl", type=float, default=60.0, metavar="SECONDS",
                   help="how long a session parked by a dropped connection "
                        "waits for its client to reattach (resume token + "
                        "bit-exact replay) before the slot is reclaimed; "
                        "parked sessions count toward --max-sessions")
    p.add_argument("--no-park", dest="park_on_disconnect",
                   action="store_false", default=True,
                   help="evict on connection drop instead of parking "
                        "(pre-survival-layer behavior)")
    p.add_argument("--replay-blocks", type=int, default=64,
                   help="per-session replay-buffer depth: how many delivered "
                        "blocks a reattaching client can have missed and "
                        "still stitch bit-exact")
    p.add_argument("--dispatch-retries", type=int, default=2,
                   help="transport-error retry budget per dispatch/readback "
                        "(seeded-jitter backoff; an exhausted budget "
                        "quarantines the session instead of evicting)")
    p.add_argument("--tick-deadline", type=float, default=None,
                   metavar="SECONDS",
                   help="per-tick wall deadline: a tick that overruns is "
                        "marked suspect, the device is fenced via the "
                        "preflight probe, and the hit feeds the degradation "
                        "ladder (never kills anything — environment "
                        "contract); default: no watchdog")
    p.add_argument("--no-ladder", dest="ladder", action="store_false",
                   default=True,
                   help="disable the degradation ladder (overload control: "
                        "super-tick shrink -> tap off -> shed-to-park, "
                        "driven by queue-wait p95 and deadline hits)")
    p.add_argument("--trace", action="store_true",
                   help="enable causal tracing (disco_tpu.obs.trace): every "
                        "traced block records a span chain (enqueue -> "
                        "dispatch -> readback -> deliver -> tap) into the "
                        "--obs-log, rendered by `disco-obs trace`; strict "
                        "no-op for pre-span clients")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the flight recorder (disco_tpu.obs.flight): a "
                        "bounded in-memory ring of recent events/spans, "
                        "dumped atomically under DIR on quarantine, park, "
                        "watchdog trip, ladder step-up, sentinel trip or "
                        "chaos crash — post-mortems with zero steady-state "
                        "I/O")
    p.add_argument("--flight-capacity", type=int, default=256,
                   help="flight-ring depth per subsystem (entries)")
    p.add_argument("--promote-dir", default=None, metavar="DIR",
                   help="arm live model promotion (disco_tpu.promote): DIR "
                        "holds the digest-addressed weight-generation store, "
                        "the ACTIVE pointer and the rollout ledger; staged "
                        "candidates are canaried onto a fraction of live "
                        "model-mask sessions at an atomic block boundary, "
                        "SDR/SLO-gated, then promoted or rolled back — "
                        "checkpoints dropped into DIR/incoming are staged "
                        "automatically")
    p.add_argument("--canary-frac", type=float, default=0.25,
                   help="fraction of live model-mask sessions canaried onto "
                        "a candidate generation (at least one session when "
                        "any exist; with --promote-dir)")
    p.add_argument("--sdr-gate-db", type=float, default=None, metavar="DB",
                   help="demote a candidate whose mean canary SDR falls more "
                        "than this many dB below the incumbent's over the "
                        "canary window (scores arrive via the promotion "
                        "controller's offer_score API); default: no SDR leg "
                        "— the gate judges SLO targets and window "
                        "completion alone")
    p.add_argument("--no-slo-gate", dest="slo_gate", action="store_false",
                   default=True,
                   help="do not judge the disco-obs slo serve targets in "
                        "the promotion gate (with --promote-dir)")
    p.add_argument("--gen-gc-keep", type=int, default=None, metavar="N",
                   help="bound the generation store after each promotion: "
                        "keep ACTIVE, the rollout's incumbent, every "
                        "generation pinned by a live session or in-flight "
                        "rollout, and the last N by staging order; collect "
                        "the rest (with --promote-dir; default: no GC — "
                        "the store grows without bound)")
    p.add_argument("--train", action="store_true",
                   help="run the co-resident trainer (disco_tpu.flywheel."
                        "resident): train-step slices interleaved on the "
                        "dispatch thread between serve ticks, consuming the "
                        "--tap-dir shards with ledger-verified resume and "
                        "publishing generations into --promote-dir on a "
                        "cadence; ladder-throttled (see "
                        "--train-throttle-rung), crash-restartable from "
                        "--train-dir (requires --tap-dir)")
    p.add_argument("--train-dir", default=None, metavar="DIR",
                   help="the resident trainer's working directory (ledger "
                        "+ rolling atomic checkpoint; default: "
                        "<--tap-dir>/resident)")
    p.add_argument("--train-batch-size", type=int, default=8,
                   help="resident trainer batch size")
    p.add_argument("--train-steps-per-tick", type=int, default=4,
                   help="train-step budget per scheduler tick — the "
                        "interleaving grain against serve dispatch")
    p.add_argument("--train-publish-every", type=int, default=1,
                   metavar="EPOCHS",
                   help="publish cadence in completed epochs (with "
                        "--promote-dir)")
    p.add_argument("--train-publish", choices=["improved", "always"],
                   default="improved",
                   help="publish policy: 'improved' stages only "
                        "best-so-far epochs (the fit() gate), 'always' "
                        "stages every cadence epoch")
    p.add_argument("--train-throttle-rung", type=int, default=1,
                   help="degradation-ladder rung at/above which a tick "
                        "trains ZERO steps (serve overload pauses training "
                        "before it costs serve SLOs)")
    p.add_argument("--train-win-len", type=int, default=None,
                   help="frames per training window (default: the tapped "
                        "block length; must fit inside one block)")
    p.add_argument("--train-max-epochs", type=int, default=None,
                   help="stop training after this many completed epochs "
                        "(default: train as long as the server runs)")
    p.add_argument("--train-recent-shards", type=int, default=None,
                   metavar="N",
                   help="sliding-window corpus: each epoch consumes only "
                        "the newest N tap shards (default: the whole "
                        "directory — epoch cost then grows with uptime)")
    add_tap_args(p)
    add_fault_args(p)
    add_preflight_arg(p, what="the server")
    add_obs_log_arg(p, what="serving")
    return p


def main(argv=None):
    """``disco-serve`` console entry point."""
    args = build_parser().parse_args(argv)
    args.fault_spec = resolve_fault_spec(args)
    with obs_session(args, tool="disco-serve"):
        if args.trace:
            from disco_tpu.obs import trace as obs_trace

            obs_trace.enable()
        if args.flight_dir:
            from disco_tpu.obs import flight as obs_flight

            obs_flight.enable(dump_dir=args.flight_dir,
                              capacity=args.flight_capacity)
        preflight = run_preflight(args)
        tap = resolve_tap(args)
        from disco_tpu.runs import GracefulInterrupt
        from disco_tpu.serve import EnhanceServer

        promote = None
        if args.promote_dir:
            from pathlib import Path

            from disco_tpu.promote.controller import PromotionController

            promote = PromotionController(
                args.promote_dir,
                canary_frac=args.canary_frac,
                sdr_gate_db=args.sdr_gate_db,
                slo_gate=args.slo_gate,
                gc_keep_last=args.gen_gc_keep,
                watch_dir=Path(args.promote_dir) / "incoming",
            )
        resident = None
        if args.train:
            if not args.tap_dir:
                raise SystemExit("--train needs --tap-dir (the shard "
                                 "directory the trainer consumes)")
            from pathlib import Path

            from disco_tpu.flywheel.resident import ResidentTrainer

            resident = ResidentTrainer(
                args.tap_dir,
                args.train_dir or Path(args.tap_dir) / "resident",
                promote_dir=args.promote_dir,
                batch_size=args.train_batch_size,
                win_len=args.train_win_len,
                steps_per_tick=args.train_steps_per_tick,
                publish_every=args.train_publish_every,
                publish=args.train_publish,
                throttle_rung=args.train_throttle_rung,
                max_epochs=args.train_max_epochs,
                recent_shards=args.train_recent_shards,
            )
        srv = EnhanceServer(
            host=args.host, port=args.port, unix_path=args.unix,
            max_sessions=args.max_sessions,
            max_queue_blocks=args.max_queue_blocks,
            max_blocks_per_tick=args.max_blocks_per_tick,
            blocks_per_super_tick=args.blocks_per_super_tick,
            overlap_readback=args.overlap_readback,
            allow_chained=args.allow_chained,
            max_backlog=args.max_backlog,
            tick_interval_s=args.tick_interval,
            state_dir=args.state_dir,
            fault_spec=args.fault_spec,
            tap=tap,
            park_on_disconnect=args.park_on_disconnect,
            park_ttl_s=args.park_ttl,
            replay_blocks=args.replay_blocks,
            dispatch_retries=args.dispatch_retries,
            tick_deadline_s=args.tick_deadline,
            ladder=args.ladder,
            promote=promote,
            resident=resident,
            run_info={"preflight": preflight, "state_dir": args.state_dir,
                      "promote_dir": args.promote_dir,
                      "train": bool(args.train),
                      "max_sessions": args.max_sessions,
                      "blocks_per_super_tick": args.blocks_per_super_tick,
                      "allow_chained": args.allow_chained,
                      "park_ttl_s": args.park_ttl,
                      "tick_deadline_s": args.tick_deadline,
                      "ladder": bool(args.ladder),
                      "trace": bool(args.trace),
                      "flight_dir": args.flight_dir,
                      "tap_dir": args.tap_dir},
        )
        try:
            with GracefulInterrupt() as stopped:
                srv.serve_forever()
        finally:
            if tap is not None:
                stats = tap.close()
                print(f"flywheel tap: {stats['shards_written']} shard(s), "
                      f"{stats['blocks_accepted']} block(s) spooled, "
                      f"{stats['blocks_dropped']} dropped under "
                      f"{args.tap_dir}")
            if resident is not None:
                st = resident.stats()
                print(f"resident trainer: {st['epochs_done']} epoch(s), "
                      f"{st['steps_total']} step(s), "
                      f"{st['generations_published']} generation(s) "
                      f"published")
        if stopped():
            n = len(srv.checkpoints)
            where = f" under {args.state_dir}" if n else ""
            print(f"interrupted — drained gracefully; {n} live session(s) "
                  f"checkpointed{where}"
                  + ("; clients resume by reopening with their session id"
                     if n else ""))
        return srv


if __name__ == "__main__":
    main()
