"""Command-line entry points — the L3/L5 argparse surface of the reference
(SURVEY.md §3), one module per reference CLI:

=====================  ===============================================
``disco-gen``          gen_disco/convolve_signals.py (room simulation)
``disco-gen-meetit``   gen_meetit/convolve_signals.py
``disco-mix``          gen_disco/mix_convolved_signals.py (PostGenerator)
``disco-tango``        speech_enhancement/tango.py (enhancement)
``disco-get-z``        speech_enhancement/get_z_signals.py (z export)
``disco-train``        dnn/engine/train.py (CRNN training)
``disco-lists``        dnn/data/lists_to_load.py (input lists)
``disco-download``     pre_generation downloaders (freesound/csv clean)
=====================  ===============================================

Every corpus-scale CLI takes ``--rirs start count`` and is idempotent, so
cluster job arrays shard the corpus exactly as the reference does
(SURVEY.md §2.9 data-parallel row).
"""
from disco_tpu.cli import bench_milestones, download, gen_disco, gen_meetit, get_z, lists, mix, tango, train

__all__ = ["bench_milestones", "download", "gen_disco", "gen_meetit", "get_z", "lists", "mix", "tango", "train"]
