"""Mixing-pass CLI (PostGenerator) — target + noise at random SNR, STFTs,
ideal masks.

Mirrors reference ``gen_disco/mix_convolved_signals.py:9-33`` (the
``args.scene`` vs ``--scenario`` flag-mismatch bug is not reproduced,
SURVEY.md §7)."""
from __future__ import annotations

import argparse

from disco_tpu.cli.common import add_noise_arg, add_rirs_arg, add_scenario_arg, snr_value
from disco_tpu.datagen.postgen import PostGenerator


def build_parser():
    """Build the ``disco-mix`` argument parser."""
    p = argparse.ArgumentParser(description="Mix convolved signals into the processed corpus")
    add_rirs_arg(p)
    add_scenario_arg(p)
    add_noise_arg(p)
    p.add_argument("--dir", "-d", dest="root", default="dataset/disco/", help="corpus root")
    p.add_argument("--snr", nargs=2, type=snr_value, default=[0, 6], help="mixture SNR range (tango.py:37)")
    p.add_argument("--no_target", action="store_true", help="skip saving clean target outputs")
    return p


def main(argv=None):
    """``disco-mix`` console entry point."""
    args = build_parser().parse_args(argv)
    rir_start, n_rirs = args.rirs
    pg = PostGenerator(
        rir_start, n_rirs, args.scenario, args.noise, args.snr, args.root,
        save_target=not args.no_target,
    )
    pg.post_process()
    return pg


if __name__ == "__main__":
    main()
