"""Z-signal export CLI — step 1 only, producing CRNN training inputs.

Mirrors reference ``speech_enhancement/get_z_signals.py:363-404`` (flags
--vad_type/--sav_dir/--rir/--scenario/--noise/--mask_z/--mod_sc; the
``load_models`` arity bug and the stale-file '.npy' check bug are not
reproduced, SURVEY.md §7)."""
from __future__ import annotations

import argparse

from disco_tpu.cli.common import add_rirs_arg, none_str, snr_value
from disco_tpu.enhance.zexport import export_z


def build_parser():
    """Build the ``disco-get-z`` argument parser."""
    p = argparse.ArgumentParser(description="Export compressed z signals (TANGO step 1)")
    p.add_argument("--vad_type", "-vt", default="irm1")
    p.add_argument("--sav_dir", "-sd", default="oracle", help="zfile name under stft_z/")
    p.add_argument("--rir", type=int, default=None, help="single RIR id (overrides --rirs)")
    add_rirs_arg(p)
    p.add_argument("--scenario", "-scene", choices=["living", "meeting", "random"], default="living")
    p.add_argument("--noise", choices=["ssn", "it", "fs"], default="fs")
    p.add_argument("--mod_sc", "-msc", default="None", help="single-channel CRNN checkpoint or None")
    p.add_argument("--dataset", default="dataset/disco/", help="corpus root")
    p.add_argument("--snr", nargs=2, type=snr_value, default=[0, 6])
    return p


def main(argv=None):
    """``disco-get-z`` console entry point."""
    args = build_parser().parse_args(argv)
    rirs = [args.rir] if args.rir is not None else range(args.rirs[0], args.rirs[0] + args.rirs[1])
    masks_fn = None
    if none_str(args.mod_sc) is not None:
        from disco_tpu.cli.tango import _load_model

        model, variables = _load_model(args.mod_sc, archi="crnn")

        def masks_fn(Y):
            import numpy as np

            from disco_tpu.enhance.inference import crnn_masks_batched

            # all node forwards in one device-resident launch
            return np.asarray(crnn_masks_batched(Y[:, 0], model, variables))

    n_done = 0
    for rir in rirs:
        try:
            done = export_z(
                args.dataset, args.scenario, rir, args.noise,
                snr_range=tuple(args.snr), zfile=args.sav_dir,
                mask_type=args.vad_type, masks_fn=masks_fn,
            )
        except FileNotFoundError:
            print(f"{rir}: input signals missing, skipped")
            continue
        n_done += bool(done)
        print(f"{rir} {'done' if done else 'already processed'}")
    return n_done


if __name__ == "__main__":
    main()
