"""Telemetry reader CLI: render an event log, diff two bench artifacts,
reconstruct causal traces, and introspect a live server.

Five subcommands:

* ``report LOG.jsonl`` — aggregate a JSONL event log (``disco_tpu.obs``
  schema) into a manifest summary, a per-stage time/call/fence table with
  the estimated tunnel-RPC overhead (n_fences × ~80 ms — the Axon cost
  model, CLAUDE.md), recompile and sentinel listings, the fault-tolerance
  story (injected faults, retry recoveries, degraded-mode entries —
  ``disco_tpu.fault`` / ``utils.resilience``), a histogram table with
  p50/p95/p99 percentiles, an online-serving section (session lifecycle,
  admission/eviction counters, request-latency percentiles —
  ``disco_tpu.serve``), and the final counter snapshot.
* ``compare OLD.json NEW.json`` — diff two bench records (either the
  driver-captured ``BENCH_r*.json`` wrapper with its ``parsed`` field, a raw
  ``bench.py`` stdout line, or an obs event log containing a
  ``bench_result`` event) into a regression verdict on the headline RTF
  and — when the baseline carries the lane — on ``corpus_clips_per_s``
  (the pipelined corpus engine's end-to-end throughput),
  ``serve_blocks_per_s`` (the online service's continuous-batching
  throughput), ``streaming_rtf_scan`` (the amortized super-tick
  streaming deployment) and ``train_steps_per_s`` / ``tap_blocks_per_s``
  (the flywheel's training-step and corpus-tap spool lanes — losing a
  measured lane is a REGRESSION, not a skip).  Exits nonzero on a regression beyond ``--threshold``,
  which is what lets ``make obs-check`` gate CI on the bench trajectory.
  ``span_overhead_ns`` (the causal-tracing hot-path delta) is judged
  lower-is-better with an absolute floor: nanosecond noise around the
  ≈0 disabled cost never flags, a real (>1 µs) regression does.
* ``trace LOG.jsonl [TRACE_ID]`` — causal-trace reconstruction
  (``disco_tpu.obs.trace``): without an id, list the log's trace ids;
  with one, render the per-hop waterfall (client block → enqueue →
  dispatch → readback → deliver → tap → train batch) with queue-wait /
  readback / delivery attribution.
* ``top ADDRESS`` — live serve introspection over the read-only
  ``status`` protocol frame (no session, never jax): session states,
  ladder rung, counters/gauges, latency percentiles, in-flight spans.
  ``--watch N`` refreshes every N seconds until interrupted.
* ``slo ADDRESS|STATUS.json`` — verdict over declared SLO targets
  (``--serve-p95-ms``, ``--queue-wait-p95-ms``, ``--max-drop-rate``,
  ``--max-evict-rate``); exits nonzero on violation, so a cron probe or
  CI smoke can gate on a live server's health.

No reference counterpart (the reference has no observability, SURVEY.md
§5.1) — this is the first-class reader the BENCH_r01–r05 trajectory never
had.  Reading telemetry never touches devices: neither this module nor the
``disco_tpu.obs`` modules it imports ever *call* into jax (obs.metrics
imports it lazily), so running the reader on the tunneled-TPU image cannot
claim the chip — the claim happens at first device use (CLAUDE.md), which
never occurs here.  (The interpreter may still *load* jax via the image's
sitecustomize or the ``disco_tpu.cli`` package import; loading is safe.)
"""
from __future__ import annotations

import argparse
import json
from pathlib import Path

from disco_tpu.obs.accounting import RPC_MS_ESTIMATE
from disco_tpu.obs.events import read_events


def build_parser():
    """Build the ``disco-obs`` argument parser."""
    p = argparse.ArgumentParser(description="Render disco_tpu telemetry")
    sub = p.add_subparsers(dest="cmd", required=True)

    rep = sub.add_parser("report", help="render a JSONL event log")
    rep.add_argument("log", help="event log written via --obs-log")

    cmp_ = sub.add_parser("compare", help="diff two bench records (old new)")
    cmp_.add_argument("old", help="baseline bench JSON (BENCH_r*.json / raw line / obs log)")
    cmp_.add_argument("new", help="candidate bench JSON")
    cmp_.add_argument("--threshold", type=float, default=0.05,
                      help="relative RTF drop that counts as a regression "
                           "(default 0.05; BENCH_r04→r05 headline noise was ~0.2%%)")

    roof = sub.add_parser(
        "roofline",
        help="per-stage roofline verdict of one bench record "
             "(measured stage_ms x modeled stage costs)")
    roof.add_argument("record", help="bench JSON (BENCH_r*.json / raw line "
                                     "/ obs log with a bench_result)")
    roof.add_argument("--peak-tflops", type=float, default=None,
                      help="dense f32 peak to judge against "
                           "(default: TPU v5e, 98)")
    roof.add_argument("--peak-gbps", type=float, default=None,
                      help="HBM bandwidth peak to judge against "
                           "(default: TPU v5e, 819)")
    roof.add_argument("--format", choices=("text", "json"), default="text",
                      help="report format")

    trc = sub.add_parser("trace", help="list / render causal traces from an event log")
    trc.add_argument("log", help="event log written via --obs-log (span events)")
    trc.add_argument("trace_id", nargs="?", default=None,
                     help="trace id to render as a waterfall; omit to list ids")
    trc.add_argument("--limit", type=int, default=20,
                     help="max trace ids to list (newest-first; default 20)")

    top = sub.add_parser("top", help="live serve introspection (status frame)")
    top.add_argument("address", help="server address: HOST:PORT or a unix socket path")
    top.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                     help="refresh every N seconds until interrupted "
                          "(default: one snapshot)")

    slo = sub.add_parser("slo", help="SLO verdict over a live server or a saved status")
    slo.add_argument("target", help="HOST:PORT, unix socket path, or a status JSON file")
    slo.add_argument("--serve-p95-ms", type=float, default=None,
                     help="delivered-block latency p95 target (ms)")
    slo.add_argument("--queue-wait-p95-ms", type=float, default=None,
                     help="enqueue-to-dispatch wait p95 target (ms)")
    slo.add_argument("--max-drop-rate", type=float, default=None,
                     help="tap drops / tap offers ceiling")
    slo.add_argument("--max-evict-rate", type=float, default=None,
                     help="evictions / finished sessions ceiling")
    return p


# -- report -----------------------------------------------------------------
def summarize(events: list[dict]) -> dict:
    """Aggregate an event list into the report structure (pure function —
    the testable core of ``report``)."""
    # LAST manifest wins (the log is append-mode: a re-used --obs-log path
    # holds one manifest per run, and the stage/counter tail being rendered
    # belongs to the newest one — same rule as the counters snapshot below)
    manifest = next((e for e in reversed(events) if e["kind"] == "manifest"), None)
    stages: dict[str, dict] = {}
    for e in events:
        if e["kind"] != "stage_end":
            continue
        s = stages.setdefault(
            e["stage"], {"calls": 0, "total_s": 0.0, "fences": 0}
        )
        s["calls"] += 1
        s["total_s"] += float(e["attrs"].get("dur_s") or 0.0)
        s["fences"] += int(e["attrs"].get("fences") or 0)
    for s in stages.values():
        s["mean_s"] = s["total_s"] / s["calls"]
    counters = next(
        (e["attrs"] for e in reversed(events) if e["kind"] == "counters"), None
    )
    n_fences = sum(s["fences"] for s in stages.values())
    if counters and "counters" in counters:
        n_fences = max(n_fences, int(counters["counters"].get("fences", 0)))
    histograms = (counters or {}).get("histograms") or {}

    # -- serve section: the online service's lifecycle + request telemetry
    session_events = [e for e in events if e["kind"] == "session"]
    cvals = (counters or {}).get("counters") or {}
    gvals = (counters or {}).get("gauges") or {}
    serve = None
    if session_events or any(k.startswith("serve") for k in cvals):
        actions: dict[str, int] = {}
        for e in session_events:
            a = e["attrs"].get("action", "?")
            actions[a] = actions.get(a, 0) + 1
        serve = {
            "sessions": actions,
            "admission_reject": int(cvals.get("admission_reject", 0)),
            "session_evicted": int(cvals.get("session_evicted", 0)),
            "serve_ticks": int(cvals.get("serve_ticks", 0)),
            "serve_blocks": int(cvals.get("serve_blocks", 0)),
            "sessions_active": gvals.get("sessions_active"),
            "queue_depth": gvals.get("queue_depth"),
            "batch_occupancy": gvals.get("batch_occupancy"),
            "latency_ms": histograms.get("serve_block_latency_ms"),
        }
    # -- flywheel section: corpus-tap spool + shard-training telemetry,
    # plus the resident trainer's generation/throttle lifecycle
    tap_events = [e for e in events if e["kind"] == "tap"]
    gen_events = [e for e in events if e["kind"] == "generation"]
    throttle_events = [e for e in events if e["kind"] == "train_throttled"]
    flywheel = None
    if (tap_events or gen_events or throttle_events
            or any(k.startswith(("tap_", "shards_")) for k in cvals)):
        flywheel = {
            "tap_blocks": int(cvals.get("tap_blocks", 0)),
            "tap_dropped": int(cvals.get("tap_dropped", 0)),
            "tap_shards_written": int(cvals.get("tap_shards_written", 0)),
            "tap_errors": int(cvals.get("tap_errors", 0)),
            "shards_skipped": int(cvals.get("shards_skipped", 0)),
            "train_steps": int(cvals.get("train_steps", 0)),
            "rotations": sum(1 for e in tap_events
                             if e["attrs"].get("action") == "shard"),
            "generations_published": sum(
                1 for e in gen_events
                if e["attrs"].get("action") == "published"),
            "generations_refused": sum(
                1 for e in gen_events
                if e["attrs"].get("action") == "refused"),
            "last_generation": next(
                (e["attrs"] for e in reversed(gen_events)
                 if e["attrs"].get("action") == "published"), None),
            "throttle_pauses": sum(
                1 for e in throttle_events
                if e["attrs"].get("action") == "paused"),
            "throttled_ticks": int(cvals.get("train_throttled_ticks", 0)),
        }
    # -- per-label recompile table: the log's own jit_trace events are the
    # run's truth (per-log scope); the jit_recompiles{label} counter series
    # (obs.accounting.recompile_label) from the final snapshot only fills
    # in labels with no events — the snapshot is PROCESS-cumulative, so for
    # a log opened mid-process it over-counts labels the run retraced.
    by_label: dict[str, int] = {}
    for e in events:
        if e["kind"] == "jit_trace":
            by_label[e["stage"]] = by_label.get(
                e["stage"], 0
            ) + int(e["attrs"].get("n_new_programs", 1))
    for name, v in (cvals or {}).items():
        # zero-valued series carry no recompile to report (defensive: a
        # stray created-but-never-incremented counter must not render)
        if (name.startswith("jit_recompiles{") and name.endswith("}")
                and int(v) > 0):
            by_label.setdefault(name[len("jit_recompiles{"):-1], int(v))
    # -- scenario-factory section: batched scene-simulation telemetry
    # (scenes/stream.py feed batches + datagen/disco.py batched chunks)
    scene_events = [e for e in events if e["kind"] == "scene"]
    scenes = None
    if scene_events or any(k in ("scene_batches", "scenes_simulated")
                           for k in cvals):
        scenes = {
            "scene_batches": int(cvals.get("scene_batches", 0)),
            "scenes_simulated": int(cvals.get("scenes_simulated", 0)),
            "stream_batches": sum(1 for e in scene_events
                                  if e.get("stage") == "scenes"),
            "datagen_batches": sum(1 for e in scene_events
                                   if e.get("stage") == "datagen"),
            "last_scene": scene_events[-1]["attrs"] if scene_events else None,
        }
    # -- causal tracing + flight dumps (the scope layer)
    span_events = [e for e in events if e["kind"] == "span"]
    traces: dict[str, int] = {}
    for e in span_events:
        t = e["attrs"].get("trace")
        traces[t] = traces.get(t, 0) + 1
    return {
        "manifest": manifest["attrs"] if manifest else None,
        "spans": len(span_events),
        "n_traces": len(traces),
        "flights": [e for e in events if e["kind"] == "flight"],
        "stages": dict(sorted(stages.items(), key=lambda kv: -kv[1]["total_s"])),
        "counters": counters,
        "recompiles": [e for e in events if e["kind"] == "jit_trace"],
        "recompiles_by_label": dict(sorted(by_label.items())),
        "sentinels": [e for e in events if e["kind"] == "sentinel"],
        "epochs": [e for e in events if e["kind"] == "epoch"],
        "clips": sum(1 for e in events if e["kind"] == "clip"),
        "watchdogs": [e for e in events if e["kind"] == "watchdog"],
        "faults": [e for e in events if e["kind"] == "fault"],
        "recoveries": [e for e in events if e["kind"] == "recovery"],
        "degraded": [e for e in events if e["kind"] == "degraded"],
        "runs": [e for e in events if e["kind"] in ("run_start", "run_resume")],
        "interrupts": [e for e in events if e["kind"] == "interrupted"],
        "warnings": [e for e in events if e["kind"] == "warning"],
        "histograms": histograms,
        "serve": serve,
        "flywheel": flywheel,
        "scenes": scenes,
        "n_events": len(events),
        "n_fences": n_fences,
        "est_rpc_s": n_fences * RPC_MS_ESTIMATE / 1e3,
    }


def render_report(summary: dict) -> str:
    """Render the ``disco-obs report`` tables from a parsed event list."""
    lines = []
    man = summary["manifest"]
    if man:
        sha = (man.get("git_sha") or "?")[:12]
        lines.append(
            f"run: git {sha}  platform={man.get('platform')} "
            f"x{man.get('device_count')} ({man.get('device_kind')})"
        )
        vers = man.get("versions") or {}
        lines.append(
            "versions: " + " ".join(f"{k}={v}" for k, v in vers.items() if v)
        )
        if man.get("config"):
            lines.append(f"config: {json.dumps(man['config'], sort_keys=True)}")
    else:
        lines.append("run: (no manifest event)")
    lines.append("")
    lines.append(f"{'stage':<22}{'calls':>7}{'total_s':>12}{'mean_ms':>12}{'fences':>8}")
    for name, s in summary["stages"].items():
        lines.append(
            f"{name:<22}{s['calls']:>7}{s['total_s']:>12.4f}"
            f"{s['mean_s'] * 1e3:>12.3f}{s['fences']:>8}"
        )
    if not summary["stages"]:
        lines.append("(no stage events)")
    lines.append(
        f"fences: {summary['n_fences']}  est RPC overhead "
        f"~{summary['est_rpc_s']:.2f}s at {RPC_MS_ESTIMATE:.0f}ms/fence"
    )
    if summary["clips"]:
        lines.append(f"clips enhanced: {summary['clips']}")

    def fmtg(v):
        return "-" if v is None else f"{v:g}"

    if summary.get("histograms"):
        lines.append("")
        lines.append(
            f"{'histogram':<28}{'count':>7}{'mean':>10}{'p50':>10}"
            f"{'p95':>10}{'p99':>10}{'max':>10}"
        )
        for name, h in sorted(summary["histograms"].items()):
            lines.append(
                f"{name:<28}{h.get('count', 0):>7}{fmtg(h.get('mean')):>10}"
                f"{fmtg(h.get('p50')):>10}{fmtg(h.get('p95')):>10}"
                f"{fmtg(h.get('p99')):>10}{fmtg(h.get('max')):>10}"
            )
    sv = summary.get("serve")
    if sv:
        lines.append("")
        sess = "  ".join(f"{k}×{v}" for k, v in sorted(sv["sessions"].items()))
        lines.append(f"serve sessions: {sess or '(none recorded)'}")
        lines.append(
            f"serve: {sv['serve_blocks']} blocks over {sv['serve_ticks']} "
            f"ticks  admission rejects={sv['admission_reject']}  "
            f"evictions={sv['session_evicted']}"
        )
        lines.append(
            f"serve gauges: sessions_active={fmtg(sv['sessions_active'])}  "
            f"queue_depth={fmtg(sv['queue_depth'])}  "
            f"batch_occupancy={fmtg(sv['batch_occupancy'])}"
        )
        lat = sv.get("latency_ms") or {}
        if lat.get("count"):
            lines.append(
                f"serve request latency (ms): p50={fmtg(lat.get('p50'))}  "
                f"p95={fmtg(lat.get('p95'))}  p99={fmtg(lat.get('p99'))}  "
                f"max={fmtg(lat.get('max'))} over {lat['count']} blocks"
            )
    fw = summary.get("flywheel")
    if fw:
        lines.append("")
        lines.append(
            f"flywheel tap: {fw['tap_blocks']} blocks spooled  "
            f"dropped={fw['tap_dropped']}  shards={fw['tap_shards_written']}"
            + (f"  errors={fw['tap_errors']}" if fw["tap_errors"] else "")
        )
        lines.append(
            f"flywheel train: {fw['train_steps']} steps  "
            f"corrupt shards skipped={fw['shards_skipped']}"
        )
        if fw.get("generations_published") or fw.get("generations_refused"):
            last = fw.get("last_generation") or {}
            tail = (f"  last={last.get('gen')} (serial {last.get('serial')}, "
                    f"epoch {last.get('epoch')})" if last else "")
            lines.append(
                f"flywheel generations: published="
                f"{fw['generations_published']}  "
                f"refused={fw['generations_refused']}{tail}"
            )
        if fw.get("throttle_pauses") or fw.get("throttled_ticks"):
            lines.append(
                f"flywheel throttle: pauses={fw['throttle_pauses']}  "
                f"throttled ticks={fw['throttled_ticks']} "
                "(ladder rung >= trainer threshold)"
            )
    sc = summary.get("scenes")
    if sc:
        lines.append("")
        lines.append(
            f"scene factory: {sc['scenes_simulated']} scenes over "
            f"{sc['scene_batches']} batched dispatches  "
            f"(stream batches={sc['stream_batches']}  "
            f"datagen chunks={sc['datagen_batches']})"
        )
        last = sc.get("last_scene") or {}
        if last:
            lines.append(
                f"scene factory last batch: n_scenes={last.get('n_scenes')}  "
                f"scenario={last.get('scenario')}  "
                f"rir_len={last.get('rir_len')}  "
                f"max_order={last.get('max_order')}"
            )
    if summary.get("spans"):
        lines.append(
            f"causal spans: {summary['spans']} over {summary['n_traces']} "
            f"trace(s) — render one with `disco-obs trace <log> <trace_id>`"
        )
    for e in summary.get("flights") or []:
        a = e["attrs"]
        lines.append(
            f"FLIGHT DUMP ({a.get('trigger')}): {a.get('path')} "
            f"[{a.get('n_entries')} entries]"
            + (f" — {a.get('reason')}" if a.get("reason") else "")
        )
    by_label = summary.get("recompiles_by_label") or {}
    if by_label:
        # per-label table (the jit_recompiles{label} counter series): which
        # entry point traced how many programs — the first thing to read
        # when `make trace-check`'s budget gate names a label
        lines.append("")
        lines.append(f"{'recompiled programs':<28}{'programs':>9}")
        for label, n in sorted(by_label.items()):
            lines.append(f"{label:<28}{n:>9}")
    def fmt6(v):
        # the schema admits any attrs dict; the reader must render partial
        # epoch events, not crash on a missing loss
        return f"{v:.6f}" if isinstance(v, (int, float)) else "-"

    for e in summary["epochs"]:
        a = e["attrs"]
        lines.append(
            f"epoch {a.get('epoch')}: train {fmt6(a.get('train_loss'))} "
            f"val {fmt6(a.get('val_loss'))} ({a.get('steps')} steps)"
        )
    for e in summary["sentinels"]:
        a = e["attrs"]
        lines.append(
            f"SENTINEL non-finite at stage {e['stage']!r}: {a.get('name')} "
            f"{a.get('n_nonfinite')}/{a.get('shape')} bad "
            f"(nan={a.get('n_nan')}, inf={a.get('n_inf')})"
        )
    for e in summary["watchdogs"]:
        lines.append(f"WATCHDOG fired: {e['attrs'].get('suspected_cause')}")
    if summary["faults"]:
        # injected faults grouped by kind; transient_error retries listed
        # individually would drown the report, so they are counted per label
        by_kind: dict[str, int] = {}
        for e in summary["faults"]:
            key = e["attrs"].get("fault", "?")
            if key == "transient_error":
                key = f"transient_error@{e['stage']}"
            by_kind[key] = by_kind.get(key, 0) + 1
        lines.append(
            "faults: " + "  ".join(f"{k}×{v}" for k, v in sorted(by_kind.items()))
        )
        for e in summary["faults"]:
            a = e["attrs"]
            if a.get("fault") == "transient_error":
                continue
            detail = "  ".join(
                f"{k}={v}" for k, v in a.items() if k not in ("fault", "blocks")
            )
            lines.append(f"  FAULT {a.get('fault')}: {detail}")
    if summary["recoveries"]:
        by_stage: dict[str, int] = {}
        for e in summary["recoveries"]:
            by_stage[e["stage"] or "?"] = by_stage.get(e["stage"] or "?", 0) + 1
        lines.append(
            "recoveries: "
            + "  ".join(f"{k}×{v}" for k, v in sorted(by_stage.items()))
        )
    for e in summary["degraded"]:
        a = e["attrs"]
        lines.append(
            f"DEGRADED mode at stage {e['stage']!r}: "
            + "  ".join(f"{k}={v}" for k, v in a.items())
        )
    for e in summary["runs"]:
        a = e["attrs"]
        if e["kind"] == "run_resume":
            lines.append(
                f"run resumed (stage {e['stage']}): {a.get('n_done')} done "
                f"verified, {a.get('n_requeued')} requeued"
                + (f" ({a.get('requeued')})" if a.get("requeued") else "")
            )
        else:
            pf = a.get("preflight")
            lines.append(
                f"run started (tool {a.get('tool')})"
                + (f"  preflight ok in {pf.get('dur_s')}s on "
                   f"{pf.get('platform')} x{pf.get('device_count')}"
                   if isinstance(pf, dict) else "")
            )
    for e in summary["interrupts"]:
        lines.append(
            f"INTERRUPTED: {e['attrs'].get('reason')} — run wound down "
            f"gracefully (resumable)"
        )
    if summary["warnings"]:
        by_stage: dict[str, int] = {}
        for e in summary["warnings"]:
            by_stage[e["stage"] or "?"] = by_stage.get(e["stage"] or "?", 0) + 1
        lines.append(
            "warnings: "
            + "  ".join(f"{k}×{v}" for k, v in sorted(by_stage.items()))
        )
        for e in summary["warnings"]:
            a = e["attrs"]
            lines.append(
                f"  WARNING at {e['stage']!r}: {a.get('reason')}"
                + (f" ({a.get('unit')})" if a.get("unit") else "")
                + (f" [{a.get('path')}]" if a.get("path") else "")
            )
    return "\n".join(lines)


# -- compare ----------------------------------------------------------------
def load_bench_record(path) -> dict:
    """Load a bench record from any of its on-disk shapes: the driver's
    ``BENCH_r*.json`` wrapper (``parsed`` field), a raw ``bench.py`` stdout
    line, or an obs event log whose ``bench_result`` event carries it."""
    path = Path(path)
    text = path.read_text()
    try:
        d = json.loads(text)
        if isinstance(d, dict) and "kind" in d and "attrs" in d:
            d = None  # a single-line event log parses as JSON too
    except json.JSONDecodeError:
        d = None
    if d is None:  # a JSONL event log: take its bench_result payload
        for e in reversed(read_events(path, validate=False)):
            if e.get("kind") == "bench_result":
                return e["attrs"]
        raise SystemExit(f"{path}: neither a bench JSON nor an event log with a bench_result")
    if isinstance(d, dict) and "parsed" in d:
        d = d["parsed"]
    if not isinstance(d, dict) or "metric" not in d:
        raise SystemExit(f"{path}: not a bench record (no 'metric' field)")
    return d


def backend_mismatch(old: dict, new: dict) -> str | None:
    """The refusal message when two bench records come from different jax
    backends, else None.  A CPU-fallback run regressing "5000x realtime →
    3x" is not a performance signal, it is a broken environment — judging
    it against an on-TPU baseline poisons the trajectory (the BENCH_r06
    hazard: ROADMAP warns a CPU record must never become the baseline).
    Records older than the ``backend`` field (BENCH_r01–r05) carry no
    claim, so comparisons stay judged unless BOTH records disagree."""
    ob, nb = old.get("backend"), new.get("backend")
    if ob and nb and ob != nb:
        return (f"refusing to judge records from different backends "
                f"(baseline '{ob}' vs candidate '{nb}') — rerun the "
                "candidate on the baseline's backend, or re-baseline "
                "deliberately")
    return None


def compare_records(old: dict, new: dict, threshold: float = 0.05) -> dict:
    """Diff two bench records into {verdict, headline, rows}.  Verdict is on
    the headline RTF: REGRESSION below ``-threshold``, IMPROVED above
    ``+threshold``, OK within — with failed lanes (null values) surfaced."""
    rows = []

    def rel(o, n):
        return (n - o) / o if (o and n is not None) else None

    for key, higher_is_better in (
        ("value", True),
        ("value_single_dispatch", True),
        ("rtf_eigh_solver", True),
        ("rtf_jacobi_solver", True),
        ("rtf_fused_solver", True),
        ("rtf_fused_step1", True),
        ("rtf_chained_clip", True),
        ("rtf_covfused", True),
        ("streaming_rtf", True),
        ("streaming_rtf_scan", True),
        ("streaming_rtf_block", True),
        ("dispatches_per_block", False),
        ("corpus_clips_per_s", True),
        ("serve_blocks_per_s", True),
        ("serve_p95_ms", False),
        ("train_steps_per_s", True),
        ("tap_blocks_per_s", True),
        ("scenes_per_s", True),
        ("flywheel_generations", True),
        ("latency_ms_frame", False),
        ("dispatch_overhead_ms", False),
        ("span_overhead_ns", False),
        ("mfu", True),
    ):
        o, n = old.get(key), new.get(key)
        if o is None and n is None:
            continue
        rows.append({"key": key, "old": o, "new": n, "rel": rel(o, n),
                     "higher_is_better": higher_is_better})
    for sk in sorted(set(old.get("stage_ms") or {}) | set(new.get("stage_ms") or {})):
        o = (old.get("stage_ms") or {}).get(sk)
        n = (new.get("stage_ms") or {}).get(sk)
        rows.append({"key": f"stage_ms.{sk}", "old": o, "new": n,
                     "rel": rel(o, n), "higher_is_better": False})
    # the meter round's per-stage roofline lanes (bench.py x
    # analysis/meter/stages.py): achieved MFU and HBM GB/s per timed stage
    for table in ("mfu_by_stage", "hbm_gbps_by_stage"):
        for sk in sorted(set(old.get(table) or {}) | set(new.get(table) or {})):
            o = (old.get(table) or {}).get(sk)
            n = (new.get(table) or {}).get(sk)
            rows.append({"key": f"{table}.{sk}", "old": o, "new": n,
                         "rel": rel(o, n), "higher_is_better": True})

    o, n = old.get("value"), new.get("value")
    if n is None:
        verdict, detail = "REGRESSION", "candidate headline RTF is null (failed run)"
    elif o is None:
        verdict, detail = "UNKNOWN", "baseline headline RTF is null"
    else:
        r = (n - o) / o
        if r < -threshold:
            verdict = "REGRESSION"
        elif r > threshold:
            verdict = "IMPROVED"
        else:
            verdict = "OK"
        detail = f"headline rtf {o:g} → {n:g} ({r:+.1%}, threshold ±{threshold:.0%})"

    # Secondary lanes — the corpus engine's clips/s, the online service's
    # blocks/s, and (since the hot-path fusion round) the roofline lanes:
    # mfu plus the two dominant stage times the fusion targets
    # (stage_ms.stft_x3 / stage_ms.step2_exchange_mwf, lower is better).
    # Each is judged alongside the RTF, and only when the BASELINE carries
    # the lane: older records don't, and their absence must not flag — but
    # a candidate that LOST a measured lane is a regression, not a skip.
    def lane(rec, key):
        for table in ("stage_ms", "mfu_by_stage", "hbm_gbps_by_stage"):
            if key.startswith(table + "."):
                return (rec.get(table) or {}).get(key[len(table) + 1:])
        return rec.get(key)

    # floor: an absolute value below which a relative drop never flags —
    # the span-overhead lane hovers at the ≈0 ns disabled cost, where
    # nanosecond scheduler noise would otherwise read as a 2x regression
    gated_lanes = [
        ("streaming_rtf_scan", "streaming-scan", "x realtime", True, None),
        ("corpus_clips_per_s", "corpus", "clips/s", True, None),
        ("serve_blocks_per_s", "serve", "blocks/s", True, None),
        ("train_steps_per_s", "train", "steps/s", True, None),
        ("tap_blocks_per_s", "tap", "blocks/s", True, None),
        # the scenario-factory lane: batched scene-simulation throughput
        # (one compiled program + one batched readback per scene batch)
        ("scenes_per_s", "scenes", "scenes/s", True, None),
        # flywheel lanes: promotion latency (lower is better; CPU smoke
        # rollouts run whole canary windows, so floor sub-10s jitter) and
        # the live-loop generation count (a candidate that LOST a lane —
        # None against a measured baseline — is the regression that
        # matters, not the counts themselves)
        ("tap_to_promotion_ms", "tap-to-promotion", "ms", False, 10_000.0),
        ("flywheel_generations", "generations", "", True, None),
        ("model_promotions", "promotions", "", True, None),
        ("span_overhead_ns", "span-overhead", "ns", False, 1000.0),
        ("mfu", "mfu", "", True, None),
        # the disco-chain lanes: the whole-clip one-program RTF and the
        # fused step-1 RTF, judged like every other lane once a baseline
        # carries them
        ("rtf_fused_step1", "fused step1", "x realtime", True, None),
        ("rtf_chained_clip", "chained clip", "x realtime", True, None),
        ("stage_ms.stft_x3", "stft stage", "ms", False, None),
        ("stage_ms.step1_local_mwf", "step1 stage", "ms", False, None),
        ("stage_ms.step2_exchange_mwf", "step2 stage", "ms", False, None),
    ]
    # the per-stage roofline lanes are dynamic: every stage the BASELINE
    # measured is gated (the r04/r05 records predate the tables and gate
    # nothing; a candidate losing a measured stage lane = REGRESSION)
    for table, label in (("mfu_by_stage", "mfu"),
                         ("hbm_gbps_by_stage", "hbm GB/s")):
        for sk in sorted(old.get(table) or {}):
            gated_lanes.append(
                (f"{table}.{sk}", f"{label}[{sk}]", "", True, None))
    for key, label, unit, higher_is_better, floor in gated_lanes:
        o_lane, n_lane = lane(old, key), lane(new, key)
        if o_lane is None:
            continue
        if n_lane is None:
            lane_verdict = "REGRESSION"
            lane_detail = f"{key} lost (null in candidate)"
        else:
            rl = (n_lane - o_lane) / o_lane if o_lane else 0.0
            good = rl if higher_is_better else -rl
            lane_verdict = ("REGRESSION" if good < -threshold
                            else "IMPROVED" if good > threshold else "OK")
            if (lane_verdict == "REGRESSION" and floor is not None
                    and n_lane <= floor):
                lane_verdict = "OK"   # sub-floor noise, not a regression
            lane_detail = f"{label} {o_lane:g} → {n_lane:g} {unit} ({rl:+.1%})".rstrip()
        detail = f"{detail}; {lane_detail}"
        if lane_verdict == "REGRESSION":
            verdict = "REGRESSION"
        elif lane_verdict == "IMPROVED" and verdict == "OK":
            verdict = "IMPROVED"
    return {"verdict": verdict, "detail": detail, "rows": rows}


def render_compare(diff: dict) -> str:
    """Render the ``disco-obs compare`` verdict lines."""
    lines = [f"{'metric':<28}{'old':>14}{'new':>14}{'delta':>10}"]
    for r in diff["rows"]:
        fmt = lambda v: "-" if v is None else f"{v:g}"
        delta = "-" if r["rel"] is None else f"{r['rel']:+.1%}"
        lines.append(f"{r['key']:<28}{fmt(r['old']):>14}{fmt(r['new']):>14}{delta:>10}")
    lines.append(f"VERDICT: {diff['verdict']} — {diff['detail']}")
    return "\n".join(lines)


# -- trace / top / slo -------------------------------------------------------
def parse_address(target: str):
    """``HOST:PORT`` -> (host, port) tuple; anything else is a unix-socket
    path (the two shapes ``disco-serve`` binds)."""
    host, sep, port = target.rpartition(":")
    if sep and port.isdigit():
        return (host or "127.0.0.1", int(port))
    return target


def cmd_trace(args):
    """``disco-obs trace``: list trace ids, or render one waterfall."""
    from disco_tpu.obs import trace as obs_trace

    events = read_events(args.log)
    if args.trace_id is None:
        ids = obs_trace.trace_ids(events)
        if not ids:
            print("(no span events in this log — run with tracing enabled: "
                  "disco-serve --trace, or obs.trace.enable())")
            return ids
        print(f"{len(ids)} trace(s); newest {min(args.limit, len(ids))}:")
        for tid in ids[-args.limit:][::-1]:
            spans = obs_trace.spans_of(events, tid)
            stages = [e["stage"] for e in spans]
            sess = next((e["attrs"].get("session") for e in spans
                         if e["attrs"].get("session") is not None), "?")
            seq = next((e["attrs"].get("seq") for e in spans
                        if e["attrs"].get("seq") is not None), "?")
            print(f"  {tid}  session={sess} seq={seq} hops={len(stages)} "
                  f"({stages[0]}→{stages[-1]})")
        return ids
    print(obs_trace.render_waterfall(events, args.trace_id))
    return obs_trace.chain(events, args.trace_id)


def render_status(payload: dict) -> str:
    """Render one ``status_ok`` payload (the ``disco-obs top`` screen)."""
    from disco_tpu.serve.status import status_section

    lines = []
    sch = status_section(payload, "scheduler")
    lines.append(
        f"tick {sch['tick_no']} ({sch['ticks_with_work']} with work)  "
        f"pending={sch['pending_blocks']}  "
        f"super-tick={sch['blocks_per_super_tick']}  "
        + ("DRAINING" if sch["draining"] else "serving")
    )
    lad = status_section(payload, "ladder")
    if lad:
        lines.append(f"ladder: rung {lad['rung']} ({lad['mode']}), "
                     f"{lad['transitions']} transition(s)")
    sessions = status_section(payload, "sessions")
    lines.append(f"{'session':<14}{'status':<13}{'in':>6}{'done':>6}"
                 f"{'queue':>7}{'inflight':>9}")
    for s in sessions:
        lines.append(
            f"{s['id']:<14}{s['status']:<13}{s['blocks_in']:>6}"
            f"{s['blocks_done']:>6}{s['queue_depth']:>7}{s['inflight']:>9}"
            + ("  priority" if s.get("priority") else "")
        )
    if not sessions:
        lines.append("(no live sessions)")
    fmt = lambda v: "-" if v is None else f"{v:g}"
    counters = status_section(payload, "counters")
    keys = ("serve_blocks", "serve_ticks", "admission_reject",
            "session_evicted", "session_closed", "session_quarantined",
            "sessions_shed", "tap_blocks", "tap_dropped")
    lines.append("counters: " + "  ".join(
        f"{k}={counters[k]}" for k in keys if k in counters))
    gauges = status_section(payload, "gauges")
    gkeys = ("sessions_active", "sessions_parked", "queue_depth",
             "batch_occupancy", "queue_wait_p95_ms", "ladder_rung")
    lines.append("gauges:   " + "  ".join(
        f"{k}={fmt(gauges[k])}" for k in gkeys if k in gauges))
    for name, h in sorted(status_section(payload, "latency").items()):
        if h.get("count"):
            lines.append(
                f"{name}: n={h['count']} p50={fmt(h.get('p50'))} "
                f"p95={fmt(h.get('p95'))} p99={fmt(h.get('p99'))} "
                f"max={fmt(h.get('max'))}"
            )
    inflight = status_section(payload, "inflight")
    if inflight.get("count"):
        lines.append(f"in-flight spans: {inflight['count']} "
                     f"(oldest {fmt(inflight.get('oldest_s'))}s)")
        for sp in inflight.get("spans") or []:
            lines.append(f"  {sp.get('trace')}  stage={sp.get('stage')} "
                         f"session={sp.get('session')} seq={sp.get('seq')} "
                         f"age={fmt(sp.get('age_s'))}s")
    return "\n".join(lines)


def cmd_top(args):
    """``disco-obs top``: one status snapshot, or a --watch loop."""
    import time as time_mod

    from disco_tpu.serve.status import fetch_status

    address = parse_address(args.address)
    while True:
        payload = fetch_status(address)
        print(render_status(payload))
        if args.watch is None:
            return payload
        print("-" * 72)
        time_mod.sleep(args.watch)


def cmd_slo(args):
    """``disco-obs slo``: judge a live server (or saved status JSON)
    against the declared targets; exit 1 on violation."""
    from disco_tpu.serve.status import evaluate_slo, fetch_status

    if Path(args.target).is_file():
        payload = json.loads(Path(args.target).read_text())
    else:
        payload = fetch_status(parse_address(args.target))
    targets = {}
    for flag, name in (("serve_p95_ms", "serve_p95_ms"),
                       ("queue_wait_p95_ms", "queue_wait_p95_ms"),
                       ("max_drop_rate", "max_drop_rate"),
                       ("max_evict_rate", "max_evict_rate")):
        v = getattr(args, flag)
        if v is not None:
            targets[name] = v
    verdict = evaluate_slo(payload, targets)
    fmt = lambda v: "-" if v is None else f"{v:g}"
    for c in verdict["checks"]:
        mark = "ok " if c["ok"] else "VIOLATED"
        print(f"{c['name']:<22}{fmt(c['value']):>12}  target {fmt(c['target']):>10}  {mark}")
    print(f"SLO VERDICT: {verdict['verdict']}")
    if verdict["verdict"] != "OK":
        raise SystemExit(1)
    return verdict


def cmd_roofline(args):
    """``disco-obs roofline``: the per-stage roofline table of one bench
    record.  The ONE disco-obs subcommand that traces programs (to cost
    the stages at the record's workload), so it forces the CPU backend
    first — rendering a roofline must never claim the tunneled chip."""
    record = load_bench_record(args.record)
    from disco_tpu.analysis.trace.check import ensure_cpu

    ensure_cpu()
    from disco_tpu.obs import roofline

    result = roofline.stage_verdicts(
        record,
        peak_tflops=(args.peak_tflops if args.peak_tflops is not None
                     else roofline.PEAK_TFLOPS),
        peak_gbps=(args.peak_gbps if args.peak_gbps is not None
                   else roofline.PEAK_GBPS),
    )
    if args.format == "json":
        print(json.dumps(result, indent=2))
    else:
        print(roofline.render(result))
    return result


def main(argv=None):
    """``disco-obs`` console entry point."""
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        summary = summarize(read_events(args.log))
        print(render_report(summary))
        return summary
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "top":
        return cmd_top(args)
    if args.cmd == "slo":
        return cmd_slo(args)
    if args.cmd == "roofline":
        return cmd_roofline(args)
    old_rec = load_bench_record(args.old)
    new_rec = load_bench_record(args.new)
    refusal = backend_mismatch(old_rec, new_rec)
    if refusal:
        import sys

        # REFUSE, do not judge: exit 2 (usage-class), distinct from the
        # regression exit 1, so CI can tell "wrong comparison" from
        # "slower code"
        print(f"disco-obs compare: {refusal} "
              f"({args.old} vs {args.new})", file=sys.stderr)
        raise SystemExit(2)
    diff = compare_records(old_rec, new_rec, args.threshold)
    print(render_compare(diff))
    if diff["verdict"] == "REGRESSION":
        raise SystemExit(1)
    return diff


if __name__ == "__main__":
    main()
