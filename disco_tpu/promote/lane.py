"""The server-side model-mask lane: per-block CRNN masks from a generation.

Sessions opened with ``SessionConfig(masks="model")`` send blocks WITHOUT
``mask_z``/``mask_w``; the scheduler fills them at dispatch time from the
session's current weight generation through :func:`block_masks` — one
batched device launch over the block's K nodes
(:func:`disco_tpu.enhance.inference.crnn_masks_batched`), using each
node's reference-mic magnitude as the single CRNN input channel (the
reference's local single-channel inference path, tango.py:211-215) and the
resulting sigmoid mask for BOTH the compression (``mask_z``) and MWF
(``mask_w``) roles.

Determinism contract (what ``make promote-check`` pins): the mask is a
pure function of ``(Y block, generation weights)`` — same block, same
generation → bit-identical masks, host-side or replayed offline.  The jit
program cache is shared across generations (the flax module instance is
cached per architecture in :func:`disco_tpu.promote.store.model_for_arch`;
weights enter as a traced argument), so a hot swap changes numbers, never
programs — the throughput-parity contract of the atomic swap.

No reference counterpart: the reference computes masks inside its offline
per-clip loop (tango.py:188-249); serving them per streamed block against
a swappable generation is new.
"""
from __future__ import annotations

import numpy as np

from disco_tpu.enhance.inference import crnn_masks_batched
from disco_tpu.utils.transfer import to_device


def block_masks(Y, model, variables, *, ref_mic: int = 0) -> np.ndarray:
    """(K, F, T) float32 masks for one (K, C, F, T) complex block.

    ``variables`` may be host or device trees (the scheduler caches them
    on device per generation); the complex block crosses to the device
    through :func:`disco_tpu.utils.transfer.to_device` (tunnel-safe), and
    only the real-valued masks come back.

    Reference counterpart: the CRNN branch of ``get_mask``
    (tango.py:211-215) — here per served block instead of per clip.
    """
    Y = np.asarray(Y)
    Ys = to_device(np.ascontiguousarray(Y[:, ref_mic]))   # (K, F, T) complex
    masks = crnn_masks_batched(
        Ys, model, variables, win_len=int(model.input_shape[1]))
    return np.asarray(masks, dtype=np.float32)
