"""The generation store: immutable, digest-addressed CRNN weight bundles.

A *weight generation* is the unit of live rollout: the inference slice of a
training checkpoint (``params`` + ``batch_stats`` — never the optimizer
state) serialized to canonical bytes, named by the digest of those bytes,
and written once through :func:`disco_tpu.io.atomic.atomic_write` so a
generation on disk is either complete or absent — no reader can ever
observe a torn weight file (the repo-wide crash-safety invariant the
``pre_swap`` chaos leg of ``make promote-check`` pins).

Layout under one promote dir::

    <root>/generations/<gen_id>/weights.msgpack   immutable weight bytes
    <root>/generations/<gen_id>/meta.json         arch kwargs + provenance
    <root>/ACTIVE                                 gen_id of the live generation
    <root>/rollouts.jsonl                         the rollout RunLedger

``meta.json`` is written AFTER the weights (its presence marks the
generation complete), and ``ACTIVE`` is a one-line pointer file replaced
atomically — the restart source of truth for which generation every
resumed session adopts.

Staging is idempotent (same weights → same digest → same generation) and
**ledger-aware**: a checkpoint published from a mid-epoch-interrupted
trainer — file-complete on disk but from a run whose latest ``epoch:*``
ledger unit is still ``in_flight`` — is refused with
:class:`PublishRefused` naming the unit, because at the file level a
partially-trained checkpoint is indistinguishable from a finished one
(the ``mid_epoch`` chaos regression in tests/test_promote.py).

No reference counterpart: the reference trains once to a bare ``.torch``
file and has no rollout story (SURVEY.md §4, §5.1).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
from pathlib import Path

from disco_tpu.io.atomic import atomic_write, file_digest, write_bytes_atomic
from disco_tpu.runs.ledger import RunLedger

#: The inference slice of a training checkpoint (training.save_checkpoint
#: payload keys) that a generation carries.  Optimizer state stays behind.
WEIGHT_KEYS = ("params", "batch_stats")

#: Name of the atomic pointer file naming the live generation.
ACTIVE_FILE = "ACTIVE"


class PublishRefused(RuntimeError):
    """A candidate checkpoint was refused at the publish seam.  ``unit``
    names the offending run-ledger unit (e.g. ``"epoch:3"``) when the
    refusal came from an interrupted training run."""

    def __init__(self, message: str, unit: str | None = None):
        super().__init__(message)
        self.unit = unit


@dataclasses.dataclass(frozen=True)
class Generation:
    """One immutable staged weight generation.

    No reference counterpart (module docstring)."""

    gen_id: str        # "g" + first 12 hex chars of the weight digest
    path: Path         # <root>/generations/<gen_id>
    digest: str        # "sha256:<hex>" over the canonical weight bytes
    serial: int        # staging order (1-based) — the weight_generation gauge
    arch: dict         # build_crnn(**arch) kwargs
    meta: dict         # full meta.json payload

    @property
    def weights_path(self) -> Path:
        return self.path / "weights.msgpack"


def _canonical(tree):
    """Recursively key-sort a pytree-of-dicts so the serialized bytes (and
    therefore the generation digest) do not depend on dict insertion order
    — staging the same weights from a live trainer and from a restored
    checkpoint must land on the same generation."""
    if isinstance(tree, dict):
        return {k: _canonical(tree[k]) for k in sorted(tree)}
    return tree


def _ledger_in_flight_epoch(ledger_path) -> str | None:
    """The first ``epoch:*`` unit whose latest recorded state is still
    ``in_flight`` (an interrupted training run), or None for a clean run."""
    latest = RunLedger(ledger_path).replay()
    for unit in sorted(latest):
        if unit.startswith("epoch:") and latest[unit]["state"] == "in_flight":
            return unit
    return None


# one CRNN module instance per arch: flax modules hash by structure, so a
# shared instance means every generation of the same architecture hits the
# same `_jitted_apply` / `_jitted_sliding_masks` cache entry — the jit
# caches are keyed by generation only through the traced `variables`
# argument, and a hot swap never retraces (ISSUE 17 parity contract)
_MODEL_CACHE: dict[str, object] = {}
_MODEL_CACHE_LOCK = threading.Lock()


def model_for_arch(arch: dict):
    """The (cached) CRNN module for one arch-kwargs dict.  Import of
    :func:`disco_tpu.nn.crnn.build_crnn` is deferred — the store itself is
    usable from jax-free readers (listing generations, the CLI).

    No reference counterpart (module docstring).
    """
    key = json.dumps(arch, sort_keys=True)
    with _MODEL_CACHE_LOCK:
        model = _MODEL_CACHE.get(key)
    if model is None:
        from disco_tpu.nn.crnn import build_crnn

        model, _tx = build_crnn(**arch)
        with _MODEL_CACHE_LOCK:
            model = _MODEL_CACHE.setdefault(key, model)
    return model


class GenerationStore:
    """Digest-addressed weight generations under one promote dir.

    All writes go through ``io.atomic``; all methods are safe to call from
    any thread (staging takes no lock — idempotence by digest makes
    concurrent stages of the same weights converge on the same files).

    No reference counterpart (module docstring).
    """

    def __init__(self, root):
        self.root = Path(root)
        (self.root / "generations").mkdir(parents=True, exist_ok=True)

    # -- staging -------------------------------------------------------------
    def stage_checkpoint(self, ckpt_path, *, arch: dict, ledger=None,
                         source: str | None = None) -> Generation:
        """Stage a training checkpoint (training.save_checkpoint payload)
        as a weight generation.  ``ledger``: the training run's
        :class:`~disco_tpu.runs.ledger.RunLedger` path — when given, a run
        whose latest ``epoch:*`` unit is still ``in_flight`` (a mid-epoch
        interrupted trainer) is refused with :class:`PublishRefused`
        naming the unit.  Idempotent: same weights → same generation.

        No reference counterpart (module docstring).
        """
        from flax import serialization

        ckpt_path = Path(ckpt_path)
        if ledger is not None:
            unit = _ledger_in_flight_epoch(ledger)
            if unit is not None:
                raise PublishRefused(
                    f"refusing to stage {ckpt_path.name}: training run "
                    f"ledger {Path(ledger).name} shows unit {unit!r} still "
                    f"in_flight — the checkpoint on disk predates an "
                    f"interrupted epoch and is not a finished candidate",
                    unit=unit,
                )
        try:
            payload = serialization.msgpack_restore(ckpt_path.read_bytes())
        except Exception as e:
            raise PublishRefused(
                f"refusing to stage {ckpt_path.name}: not a readable "
                f"checkpoint ({type(e).__name__}: {e})"
            ) from e
        missing = [k for k in WEIGHT_KEYS if k not in payload]
        if missing:
            raise PublishRefused(
                f"refusing to stage {ckpt_path.name}: checkpoint payload "
                f"missing {missing} (keys: {sorted(payload)})"
            )
        variables = {k: payload[k] for k in WEIGHT_KEYS}
        extra = {"source_ckpt": str(ckpt_path),
                 "source_ckpt_digest": file_digest(ckpt_path)}
        import numpy as np

        n_done = None
        if "epochs_done" in payload:
            try:
                n_done = int(np.asarray(payload["epochs_done"]).reshape(()))
                extra["epochs_done"] = float(n_done)
            except (TypeError, ValueError):
                pass
        for k in ("val_loss", "train_loss"):
            # the payload carries the whole (zero-padded) loss HISTORY —
            # the meta scalar is the last completed epoch's value
            if k in payload:
                try:
                    hist = np.asarray(payload[k], np.float64).reshape(-1)
                except (TypeError, ValueError):
                    continue
                if n_done is not None:
                    hist = hist[:n_done]
                if hist.size:
                    extra[k] = float(hist[-1])
        return self.stage_variables(variables, arch=arch, source=source,
                                    **extra)

    def stage_variables(self, variables: dict, *, arch: dict,
                        source: str | None = None, **extra) -> Generation:
        """Stage an in-memory ``{"params", "batch_stats"}`` dict (the live
        ``fit()`` publish path and the check harness).  Writes weights then
        meta, each atomically; returns the (possibly pre-existing)
        :class:`Generation`.

        No reference counterpart (module docstring).
        """
        from flax import serialization

        variables = {k: variables[k] for k in WEIGHT_KEYS}
        blob = serialization.msgpack_serialize(
            serialization.to_state_dict(_canonical(variables)))
        digest = "sha256:" + hashlib.sha256(blob).hexdigest()
        gen_id = "g" + digest.split(":", 1)[1][:12]
        gen_dir = self.root / "generations" / gen_id
        meta_path = gen_dir / "meta.json"
        if meta_path.exists():
            return self.get(gen_id)
        gen_dir.mkdir(parents=True, exist_ok=True)
        write_bytes_atomic(gen_dir / "weights.msgpack", blob)
        meta = {
            "gen": gen_id,
            "digest": digest,
            "serial": len(self.list_ids()) + 1,
            "arch": dict(arch),
            "source": source,
            "staged_t": time.time(),
            **extra,
        }
        with atomic_write(meta_path, mode="w", encoding="utf-8") as fh:
            json.dump(meta, fh, sort_keys=True, indent=1)
            fh.write("\n")
        return Generation(gen_id=gen_id, path=gen_dir, digest=digest,
                          serial=int(meta["serial"]), arch=dict(arch),
                          meta=meta)

    # -- reading -------------------------------------------------------------
    def list_ids(self) -> list:
        """Complete generation ids (meta.json present), staging order.

        No reference counterpart (module docstring)."""
        gens = []
        base = self.root / "generations"
        for d in base.iterdir() if base.is_dir() else ():
            if (d / "meta.json").is_file():
                gens.append(self.get(d.name))
        return [g.gen_id for g in sorted(gens, key=lambda g: g.serial)]

    def get(self, gen_id: str) -> Generation:
        """Load one generation's metadata (raises ``FileNotFoundError``
        for an unknown or incomplete generation).

        No reference counterpart (module docstring)."""
        gen_dir = self.root / "generations" / gen_id
        meta = json.loads((gen_dir / "meta.json").read_text())
        return Generation(gen_id=gen_id, path=gen_dir,
                          digest=meta["digest"], serial=int(meta["serial"]),
                          arch=dict(meta["arch"]), meta=meta)

    def load(self, gen_id: str):
        """(model, variables) for one generation — the CRNN module (cached
        per arch, see :func:`model_for_arch`) and the restored host-side
        ``{"params", "batch_stats"}`` dict.  The weight file is
        digest-verified first: a torn or tampered file fails loudly here,
        never as silent garbage masks.

        No reference counterpart (module docstring).
        """
        gen = self.get(gen_id)
        actual = file_digest(gen.weights_path)
        if actual != gen.digest:
            raise PublishRefused(
                f"generation {gen_id}: weight file digest {actual} does not "
                f"match staged digest {gen.digest} — torn or corrupt file"
            )
        from flax import serialization

        variables = serialization.msgpack_restore(
            gen.weights_path.read_bytes())
        return model_for_arch(gen.arch), variables

    # -- the ACTIVE pointer --------------------------------------------------
    def active(self) -> str | None:
        """gen_id of the live generation, or None before first activation.

        No reference counterpart (module docstring)."""
        path = self.root / ACTIVE_FILE
        if not path.is_file():
            return None
        gen_id = path.read_text().strip()
        return gen_id or None

    def set_active(self, gen_id: str) -> None:
        """Atomically repoint ``ACTIVE`` (the promotion commit point: after
        this rename, every restart adopts ``gen_id``).

        No reference counterpart (module docstring)."""
        self.get(gen_id)   # unknown/incomplete generations must not go live
        write_bytes_atomic(self.root / ACTIVE_FILE, (gen_id + "\n").encode())

    # -- retention -------------------------------------------------------------
    def collect(self, *, keep_last: int, pinned=()) -> list:
        """Bounded retention: delete every complete generation EXCEPT the
        ACTIVE one, the ``keep_last`` most recently staged (by serial),
        the candidate/incumbent of any rollout whose ledger unit is still
        undecided (``in_flight`` — a crash mid-rollout must always find
        both sides of the swap on disk), and anything in ``pinned`` (the
        caller's live-session generation refs).  Returns the collected
        gen_ids, oldest first; ticks the ``generations_collected``
        counter and records one ``promotion`` ``action="collected"`` obs
        event per sweep that removed anything.

        Opt-in only — nothing in the store calls this on its own.  Under
        a continuous trainer the store otherwise grows one immutable
        generation per publish cadence, without bound.

        No reference counterpart (module docstring).
        """
        import shutil

        from disco_tpu.obs import events as obs_events
        from disco_tpu.obs.metrics import REGISTRY as obs_registry

        if keep_last < 0:
            raise ValueError(f"keep_last must be >= 0, got {keep_last}")
        ids = self.list_ids()      # serial order, oldest first
        keep = set(pinned)
        active = self.active()
        if active is not None:
            keep.add(active)
        keep.update(ids[len(ids) - keep_last:] if keep_last else ())
        for unit, rec in RunLedger(self.root / "rollouts.jsonl").replay().items():
            if not unit.startswith("rollout:") or rec["state"] != "in_flight":
                continue
            keep.add(unit.split(":", 1)[1])
            incumbent = (rec.get("attrs") or {}).get("incumbent")
            if incumbent:
                keep.add(incumbent)
        collected = []
        for gen_id in ids:
            if gen_id in keep:
                continue
            # meta first: a crash mid-delete leaves an INCOMPLETE dir
            # (no meta.json), which every reader already treats as absent
            gen_dir = self.root / "generations" / gen_id
            (gen_dir / "meta.json").unlink(missing_ok=True)
            shutil.rmtree(gen_dir, ignore_errors=True)
            collected.append(gen_id)
        if collected:
            obs_registry.counter("generations_collected").inc(len(collected))
            obs_events.record("promotion", stage="promote",
                              action="collected", gens=collected,
                              keep_last=int(keep_last), kept=len(keep))
        return collected

    # -- the rollout ledger ----------------------------------------------------
    def rollout_ledger(self) -> RunLedger:
        """The store's rollout :class:`~disco_tpu.runs.ledger.RunLedger`
        (``rollouts.jsonl``) — one ``rollout:<gen_id>`` unit per attempted
        promotion, phase carried in attrs.  Callers own closing it.

        No reference counterpart (module docstring)."""
        return RunLedger(self.root / "rollouts.jsonl")
