"""disco-promote: live SDR-gated model promotion (the fifteenth gate).

The flywheel (PR 11) ends at a checkpoint file on disk; this package turns
it into a loop: candidate CRNN weights are staged as immutable,
digest-addressed **weight generations** (:mod:`disco_tpu.promote.store`), a
configurable fraction of live sessions is canaried onto the candidate at an
atomic block boundary, an SDR/SLO gate over a bounded canary window decides
promote vs rollback, and every step is crash-drilled through the chaos
seams ``pre_swap`` / ``mid_canary`` / ``post_gate``
(:mod:`disco_tpu.promote.controller`).  ``make promote-check`` is the
hermetic drill (:mod:`disco_tpu.promote.check`).

No reference counterpart: the reference trains once and has no serving
layer to roll anything out to (SURVEY.md §5.1).
"""
from disco_tpu.promote.controller import (  # noqa: F401
    PromotionController,
    rollout_unit,
)
from disco_tpu.promote.store import (  # noqa: F401
    Generation,
    GenerationStore,
    PublishRefused,
)
