"""The promotion controller: canary → gate → promote-or-rollback, drilled.

One background thread (``disco-promote-controller``) turns staged weight
generations (:mod:`disco_tpu.promote.store`) into a survivable rollout:

1. **stage** — watch a checkpoint directory (or accept direct
   ``GenerationStore`` stages from a live trainer) and stage candidates as
   immutable digest-addressed generations; a mid-epoch-interrupted run is
   refused at this seam (:class:`~disco_tpu.promote.store.PublishRefused`).
2. **canary** — request that ``canary_frac`` of the live model-mask
   sessions swap onto the candidate.  The controller only *requests*:
   every swap is executed by the scheduler's DISPATCH thread at a
   park-checkpoint block boundary (``Scheduler._apply_generation_swaps``),
   so each session sees exactly ONE generation per block and the
   controller never touches jax (disco-race: NOT jax_ok).
3. **gate** — over a bounded canary window, judge canary SDR within
   ``sdr_gate_db`` of the incumbent (scores arrive through
   :meth:`PromotionController.offer_score`; unmeasured sides follow the
   ``evaluate_slo`` convention) plus the ``disco-obs slo`` serve targets.
4. **promote or roll back** — promotion flips the store's ``ACTIVE``
   pointer atomically after every model session adopted the candidate;
   demotion dumps the flight recorder (trigger ``demotion``, reason naming
   the failing metric) and re-parks the canary sessions onto the incumbent
   at the same atomic boundary.

Every transition is recorded in the store's rollout ledger BEFORE it takes
effect, so a crash at any chaos seam (``pre_swap`` on the dispatch thread,
``mid_canary``/``post_gate`` here) resumes deterministically: on restart,
:meth:`PromotionController.start` replays the ledger — an interrupted
``promoting`` phase whose ``ACTIVE`` already points at the candidate is
completed, anything else is rolled back, and every session re-adopts
``ACTIVE`` (``make promote-check`` pins all three legs).

No reference counterpart: the reference trains once and serves nothing
(SURVEY.md §5.1); the canary/gate/rollback ladder is the standard
progressive-delivery shape sized down to one process and one ledger.
"""
from __future__ import annotations

import collections
import threading
import time
from pathlib import Path

from disco_tpu.obs import events as obs_events
from disco_tpu.obs import flight as obs_flight
from disco_tpu.obs import trace as obs_trace
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.promote.store import GenerationStore, PublishRefused
from disco_tpu.runs import chaos

#: Rollout phases, carried in the ledger's ``phase`` attr (the ledger
#: *state* stays the closed LEDGER_STATES set: ``in_flight`` while any
#: phase is live, ``done``/``failed`` terminal).
PHASES = ("idle", "canary", "gating", "promoting", "rolling_back")


def rollout_unit(gen_id: str) -> str:
    """Ledger unit id of one promotion rollout.

    No reference counterpart (module docstring)."""
    return f"rollout:{gen_id}"


class PromotionController:
    """Drives the canary/gate/rollback ladder against one
    :class:`~disco_tpu.promote.store.GenerationStore` and one scheduler.

    Threading contract (disco-race): the controller thread never enters
    jax — it *requests* swaps into ``_pending`` and the dispatch thread
    executes them (:meth:`pending_swaps` / :meth:`note_swapped`).
    ``_lock`` guards the rollout state machine and is never held across a
    store read, a scheduler call or any I/O.

    Args:
      store: the generation store (or a promote-dir path).
      canary_frac: fraction of live model-mask sessions canaried onto a
        candidate (at least one when any exist).
      sdr_gate_db: demote when mean canary SDR falls more than this many
        dB below the incumbent's; None skips the SDR leg (scoreless
        deployments gate on SLO + window completion alone).
      slo_gate: also judge the ``disco-obs slo`` serve targets
        (``slo_targets`` overrides :data:`~disco_tpu.serve.status.DEFAULT_SLO`).
      window_blocks: canary window size — delivered candidate blocks
        needed before the gate fires.
      min_scores: minimum canary SDR samples for the SDR leg to count as
        measured.
      gate_timeout_s: wall bound on the whole rollout; a window still
        starved at the bound demotes with the window named as the failing
        metric (no evidence → no promotion).
      watch_dir: optional checkpoint directory to poll for candidates
        (``*.msgpack``; a sibling ``<stem>.ledger.jsonl`` or
        ``ledger.jsonl`` is consulted for the mid-epoch refusal).
      poll_s: controller step period.
      gc_keep_last: arm bounded generation retention — after every
        successful promotion, :meth:`~disco_tpu.promote.store.
        GenerationStore.collect` keeps ACTIVE, the just-replaced
        incumbent, every generation a live/parked session still
        references or an in-flight rollout names, and the last N staged;
        None (default) = the store grows without bound.

    No reference counterpart (module docstring).
    """

    def __init__(self, store, *, canary_frac: float = 0.25,
                 sdr_gate_db: float | None = None, slo_gate: bool = True,
                 slo_targets: dict | None = None, window_blocks: int = 32,
                 min_scores: int = 2, gate_timeout_s: float = 120.0,
                 watch_dir=None, poll_s: float = 0.05,
                 gc_keep_last: int | None = None):
        if not 0.0 <= float(canary_frac) <= 1.0:
            raise ValueError(f"canary_frac must be in [0, 1], got {canary_frac}")
        if int(window_blocks) < 1:
            raise ValueError(f"window_blocks must be >= 1, got {window_blocks}")
        if gc_keep_last is not None and int(gc_keep_last) < 0:
            raise ValueError(f"gc_keep_last must be >= 0, got {gc_keep_last}")
        self.store = store if isinstance(store, GenerationStore) else GenerationStore(store)
        self.canary_frac = float(canary_frac)
        self.sdr_gate_db = None if sdr_gate_db is None else float(sdr_gate_db)
        self.slo_gate = bool(slo_gate)
        self.slo_targets = dict(slo_targets) if slo_targets else None
        self.window_blocks = int(window_blocks)
        self.min_scores = int(min_scores)
        self.gate_timeout_s = float(gate_timeout_s)
        self.watch_dir = Path(watch_dir) if watch_dir is not None else None
        self.poll_s = float(poll_s)
        self.gc_keep_last = None if gc_keep_last is None else int(gc_keep_last)

        self.scheduler = None
        self.crashed: BaseException | None = None
        self._ledger = self.store.rollout_ledger()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._seen_ckpts: dict = {}

        self._lock = threading.Lock()
        self._phase = "idle"
        self._candidate = None          # Generation under rollout
        self._incumbent: str | None = None
        self._pending: dict = {}        # sid -> (gen_id, kind) swap requests
        self._swapped: set = set()      # sids currently on the candidate
        self._canary_ids: set = set()
        self._scores = {"canary": collections.deque(maxlen=self.window_blocks),
                        "incumbent": collections.deque(maxlen=self.window_blocks)}
        self._canary_blocks = 0
        self._window_t0: float | None = None
        self._rollout_t0: float | None = None
        self._fail_reason: str | None = None
        self._trace = None              # rollout SpanCtx (promote_* chain)

    # -- wiring ----------------------------------------------------------------
    def bind(self, scheduler) -> None:
        """Attach the scheduler this controller steers (called by
        ``Scheduler.__init__(promote=...)``).

        No reference counterpart (module docstring)."""
        self.scheduler = scheduler

    def start(self) -> None:
        """Resume any interrupted rollout from the ledger, then start the
        controller thread.

        No reference counterpart (module docstring)."""
        self._resume()
        self._thread = threading.Thread(
            target=self._run, name="disco-promote-controller", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Request controller shutdown (idempotent).

        No reference counterpart (module docstring)."""
        self._stop.set()

    def wait(self, timeout_s: float | None = 10.0) -> None:
        """Join the controller thread; inspect :attr:`crashed` afterwards
        (a ChaosCrash in the controller is surfaced there, like
        ``EnhanceServer.crashed``).

        No reference counterpart (module docstring)."""
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- dispatch-thread interface (scheduler side) ----------------------------
    def active_generation(self) -> str:
        """gen_id every newly-opened model-mask session adopts (the store's
        ``ACTIVE`` pointer — crash truth, not controller memory).

        No reference counterpart (module docstring)."""
        gen = self.store.active()
        if gen is None:
            raise RuntimeError(
                f"promote store {self.store.root} has no ACTIVE generation — "
                f"stage and activate an incumbent before serving model masks")
        return gen

    def pending_swaps(self) -> list:
        """Snapshot of requested swaps: ``[(session_id, gen_id, kind)]``
        with kind in ``canary``/``promote``/``rollback``.  The dispatch
        thread applies what it can at block boundaries and reports back
        through :meth:`note_swapped` / :meth:`note_swap_void`.

        No reference counterpart (module docstring)."""
        with self._lock:
            return [(sid, gen, kind) for sid, (gen, kind) in self._pending.items()]

    def note_swapped(self, session_id: str, gen_id: str, seq: int) -> None:
        """Dispatch-thread report: ``session_id`` now serves ``gen_id``
        from block ``seq`` on.

        No reference counterpart (module docstring)."""
        with self._lock:
            self._pending.pop(session_id, None)
            if self._candidate is not None and gen_id == self._candidate.gen_id:
                self._swapped.add(session_id)
            else:
                self._swapped.discard(session_id)

    def note_swap_void(self, session_id: str) -> None:
        """Dispatch-thread report: the session a swap was requested for is
        gone (closed/evicted/parked) — stop waiting on it.

        No reference counterpart (module docstring)."""
        with self._lock:
            self._pending.pop(session_id, None)
            self._swapped.discard(session_id)
            self._canary_ids.discard(session_id)

    def current_candidate(self) -> str | None:
        """gen_id of the generation under rollout, or None when idle (the
        scheduler's reattach staleness check).

        No reference counterpart (module docstring)."""
        with self._lock:
            return None if self._candidate is None else self._candidate.gen_id

    def note_delivery(self, session_id: str, seq: int, gen_id: str) -> None:
        """Dispatch-thread report: one block was delivered under
        ``gen_id`` — advances the canary window while the gate is open.

        No reference counterpart (module docstring)."""
        with self._lock:
            if (self._phase == "gating" and self._candidate is not None
                    and gen_id == self._candidate.gen_id):
                self._canary_blocks += 1

    def model_for(self, gen_id: str):
        """(model, host variables) for a generation — the scheduler's
        device-cache miss path (store digest-verifies the weight file).

        No reference counterpart (module docstring)."""
        return self.store.load(gen_id)

    # -- scorer interface ------------------------------------------------------
    def offer_score(self, session_id: str, seq: int, sdr_db: float, *,
                    gen: str | None = None) -> None:
        """Feed one delivered block's SDR (any thread; the check harness
        and external scorers).  ``gen`` attributes the sample to the
        candidate or incumbent side explicitly (the delivered frame's
        generation tag); without it the session's current side is used.

        No reference counterpart (module docstring)."""
        with self._lock:
            if self._phase not in ("canary", "gating") or self._candidate is None:
                return
            if gen is not None:
                side = "canary" if gen == self._candidate.gen_id else "incumbent"
            else:
                side = "canary" if session_id in self._swapped else "incumbent"
            self._scores[side].append(float(sdr_db))

    # -- the controller thread -------------------------------------------------
    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self._step()
                self._stop.wait(self.poll_s)
        except BaseException as e:  # noqa: BLE001 — deliberate last-resort
            # stash (disco-race DR007 waiver): a ChaosCrash here simulates
            # the controller's process death; the serve process must keep
            # serving on its current generations, and the harness (and
            # disco-serve) observes the death via `crashed` exactly like
            # EnhanceServer._dispatch_loop's stash.
            self.crashed = e
            obs_events.record("rollback", stage="controller", action="crashed",
                              error=f"{type(e).__name__}: {e}")

    def _step(self) -> None:
        self._scan_watch_dir()
        with self._lock:
            phase = self._phase
        if phase == "idle":
            self._maybe_begin_rollout()
        elif phase == "canary":
            self._step_canary()
        elif phase == "gating":
            self._step_gating()
        elif phase == "promoting":
            self._step_promoting()
        elif phase == "rolling_back":
            self._step_rolling_back()

    # -- staging (watch dir) ---------------------------------------------------
    def _scan_watch_dir(self) -> None:
        if self.watch_dir is None or not self.watch_dir.is_dir():
            return
        active = self.store.active()
        for path in sorted(self.watch_dir.glob("*.msgpack")):
            try:
                st = path.stat()
            except OSError:
                continue
            key = (st.st_mtime_ns, st.st_size)
            if self._seen_ckpts.get(str(path)) == key:
                continue
            self._seen_ckpts[str(path)] = key
            if active is None:
                obs_events.record(
                    "promotion", stage="stage", action="refused",
                    path=path.name,
                    reason="no ACTIVE generation to take the architecture from")
                continue
            arch = self.store.get(active).arch
            ledger = None
            for cand in (path.with_suffix(".ledger.jsonl"),
                         path.parent / "ledger.jsonl"):
                if cand.is_file():
                    ledger = cand
                    break
            try:
                gen = self.store.stage_checkpoint(
                    path, arch=arch, ledger=ledger, source=str(path))
            except PublishRefused as e:
                obs_events.record("promotion", stage="stage", action="refused",
                                  path=path.name, unit=e.unit, reason=str(e))
                continue
            with self._lock:
                queued = self._phase != "idle"
            # a candidate landing mid-rollout is QUEUED, not dropped: it is
            # staged now and picked up by _maybe_begin_rollout at the next
            # idle step (newest-wins — see the superseded marking there)
            obs_events.record("promotion", stage="stage", action="staged",
                              gen=gen.gen_id, serial=gen.serial,
                              path=path.name, queued=queued)

    # -- phase steps -----------------------------------------------------------
    def _maybe_begin_rollout(self) -> None:
        active = self.store.active()
        if active is None:
            return
        latest = self._ledger.replay()
        active_serial = self.store.get(active).serial
        eligible = []
        for gen_id in self.store.list_ids():       # staging (serial) order
            if gen_id == active:
                continue
            if self.store.get(gen_id).serial < active_serial:
                continue   # staged before the live generation: a promotion
                           # must never resurrect a superseded candidate
            rec = latest.get(rollout_unit(gen_id))
            if rec is not None and rec["state"] in ("done", "failed"):
                continue                            # already decided — never retried
            eligible.append(self.store.get(gen_id))
        if not eligible:
            return
        candidate = eligible[-1]                    # newest wins
        for stale in eligible[:-1]:
            # decide the older queued candidates DURABLY: without a
            # terminal record a failed rollout of the newest would let an
            # already-obsolete generation roll out on the next idle step
            self._ledger.mark_failed(
                rollout_unit(stale.gen_id),
                error=f"superseded by {candidate.gen_id}",
                phase="superseded", superseded_by=candidate.gen_id)
            obs_registry.counter("candidates_superseded").inc()
            obs_events.record("promotion", stage="rollout", action="superseded",
                              gen=stale.gen_id, serial=stale.serial,
                              by=candidate.gen_id)
        unit = rollout_unit(candidate.gen_id)
        self._ledger.record(unit, "in_flight", phase="canary",
                            candidate=candidate.gen_id, incumbent=active,
                            canary_frac=self.canary_frac)
        ctx = obs_trace.root("promote_stage", gen=candidate.gen_id,
                            serial=candidate.serial)
        obs_events.record("promotion", stage="rollout", action="begin",
                          gen=candidate.gen_id, serial=candidate.serial,
                          incumbent=active)
        with self._lock:
            self._phase = "canary"
            self._candidate = candidate
            self._incumbent = active
            self._pending = {}
            self._swapped = set()
            self._canary_ids = set()
            self._scores = {
                "canary": collections.deque(maxlen=self.window_blocks),
                "incumbent": collections.deque(maxlen=self.window_blocks)}
            self._canary_blocks = 0
            self._rollout_t0 = time.monotonic()
            self._window_t0 = None
            self._fail_reason = None
            self._trace = ctx

    def _model_session_ids(self) -> list:
        sched = self.scheduler
        return [] if sched is None else sched.model_session_ids()

    def _step_canary(self) -> None:
        with self._lock:
            cand = self._candidate
            have_canaries = bool(self._canary_ids)
            pending = bool(self._pending)
            t0 = self._rollout_t0
        if not have_canaries:
            eligible = sorted(self._model_session_ids())
            if not eligible:
                if time.monotonic() - t0 > self.gate_timeout_s:
                    self._decide([{"name": "canary_sessions", "value": 0,
                                   "target": 1, "ok": False}])
                return
            n = max(1, int(round(self.canary_frac * len(eligible))))
            chosen = eligible[:n]
            with self._lock:
                self._canary_ids = set(chosen)
                for sid in chosen:
                    self._pending[sid] = (cand.gen_id, "canary")
            self._trace = obs_trace.span("promote_canary", self._trace,
                                         gen=cand.gen_id, n=len(chosen))
            obs_events.record("canary", stage="assign", action="assign",
                              gen=cand.gen_id, sessions=chosen,
                              frac=self.canary_frac)
            return
        if not pending:
            with self._lock:
                if not self._swapped:      # every chosen canary vanished
                    self._canary_ids = set()
                    return
                self._phase = "gating"
                self._window_t0 = time.monotonic()
                n_live = len(self._swapped)
            obs_events.record("canary", stage="window", action="window",
                              gen=cand.gen_id, n=n_live,
                              window_blocks=self.window_blocks)

    def _step_gating(self) -> None:
        with self._lock:
            cand = self._candidate
            blocks = self._canary_blocks
            t0 = self._window_t0
        chaos.tick("mid_canary", gen=cand.gen_id, blocks=blocks)
        starved = time.monotonic() - t0 > self.gate_timeout_s
        if blocks < self.window_blocks and not starved:
            return
        if starved and blocks < self.window_blocks:
            checks = [{"name": "canary_window_blocks", "value": blocks,
                       "target": self.window_blocks, "ok": False}]
        else:
            checks = self._gate_checks()
        self._decide(checks)

    def _gate_checks(self) -> list:
        with self._lock:
            canary = list(self._scores["canary"])
            incumbent = list(self._scores["incumbent"])
        checks = []
        if self.sdr_gate_db is not None:
            mean_c = (sum(canary) / len(canary)
                      if len(canary) >= self.min_scores else None)
            mean_i = (sum(incumbent) / len(incumbent)) if incumbent else None
            if mean_c is None:
                # the operator asked for SDR gating: an unmeasured canary
                # side is a FAIL here (unlike evaluate_slo's idle-server
                # pass) — no evidence must never promote
                checks.append({"name": "canary_sdr_db", "value": None,
                               "target": None, "ok": False})
            elif mean_i is None:
                checks.append({"name": "canary_sdr_db",
                               "value": round(mean_c, 4), "target": None,
                               "ok": True})     # no incumbent baseline to defend
            else:
                target = mean_i - self.sdr_gate_db
                checks.append({"name": "canary_sdr_db",
                               "value": round(mean_c, 4),
                               "target": round(target, 4),
                               "ok": mean_c >= target})
        if self.slo_gate and self.scheduler is not None:
            from disco_tpu.serve.status import evaluate_slo, status_payload

            slo = evaluate_slo(status_payload(self.scheduler), self.slo_targets)
            checks.extend(slo["checks"])
        return checks

    def _decide(self, checks: list) -> None:
        with self._lock:
            cand = self._candidate
        ok = all(c["ok"] for c in checks)
        chaos.tick("post_gate", gen=cand.gen_id,
                   verdict="promote" if ok else "demote")
        self._trace = obs_trace.span(
            "promote_gate", self._trace, gen=cand.gen_id,
            verdict="promote" if ok else "demote",
            checks=[c["name"] for c in checks if not c["ok"]])
        if ok:
            self._begin_promote(checks)
        else:
            self._begin_rollback(checks)

    def _begin_promote(self, checks: list) -> None:
        with self._lock:
            cand = self._candidate
        self._ledger.record(rollout_unit(cand.gen_id), "in_flight",
                            phase="promoting", checks=checks)
        obs_events.record("promotion", stage="gate", action="pass",
                          gen=cand.gen_id, checks=checks)
        with self._lock:
            self._phase = "promoting"
        self._step_promoting()

    def _step_promoting(self) -> None:
        sids = set(self._model_session_ids())
        with self._lock:
            cand = self._candidate
            for sid in sids - self._swapped - set(self._pending):
                self._pending[sid] = (cand.gen_id, "promote")
            done = not self._pending and sids <= self._swapped
        if done:
            self._finish_promote()

    def _finish_promote(self) -> None:
        with self._lock:
            cand = self._candidate
        self.store.set_active(cand.gen_id)
        latency_ms = max(0.0, (time.time() - float(
            cand.meta.get("staged_t", time.time()))) * 1e3)
        self._ledger.mark_done(rollout_unit(cand.gen_id),
                               artifact_paths=(cand.weights_path,),
                               phase="done", latency_ms=round(latency_ms, 3))
        obs_registry.counter("model_promotions").inc()
        obs_registry.gauge("weight_generation").set(cand.serial)
        obs_registry.histogram("tap_to_promotion_ms").observe(latency_ms)
        obs_events.record("promotion", stage="rollout", action="promoted",
                          gen=cand.gen_id, serial=cand.serial,
                          latency_ms=round(latency_ms, 3))
        self._trace = obs_trace.span("promote_swap", self._trace,
                                     gen=cand.gen_id, action="promote")
        self._collect_generations()
        self._reset_to_idle()

    def _collect_generations(self) -> None:
        """Bounded-retention sweep after a successful promotion (only when
        ``gc_keep_last`` is set).  Pins the just-replaced incumbent plus
        every generation a live or parked session still references — the
        dispatch thread may deliver from them until the park boundary;
        :meth:`~disco_tpu.promote.store.GenerationStore.collect` itself
        pins ACTIVE and any in-flight rollout's sides.  A GC failure must
        never break the rollout path: it is demoted to a warning event.

        No reference counterpart (module docstring)."""
        if self.gc_keep_last is None:
            return
        with self._lock:
            pins = {self._incumbent}
        sched = self.scheduler
        if sched is not None:
            pins |= {s.generation for s in sched.sessions()}
            pins |= {s.generation for s in sched.parked_sessions()}
        pins.discard(None)
        try:
            self.store.collect(keep_last=self.gc_keep_last, pinned=pins)
        except Exception as e:  # noqa: BLE001 — GC is best-effort
            obs_events.record("warning", stage="promote", action="gc_failed",
                              error=f"{type(e).__name__}: {e}")

    def _begin_rollback(self, checks: list) -> None:
        failing = next(c for c in checks if not c["ok"])
        reason = (f"{failing['name']}={failing['value']}"
                  f" vs target {failing['target']}")
        with self._lock:
            cand = self._candidate
            incumbent = self._incumbent
            self._fail_reason = reason
        # the flight dump FIRST (names the failing metric), then the
        # durable intent, then the swap requests — a crash between any two
        # resumes as a rollback (the post_gate drill)
        obs_flight.auto_dump("demotion", reason=reason)
        self._ledger.record(rollout_unit(cand.gen_id), "in_flight",
                            phase="rolling_back", reason=reason,
                            metric=failing["name"], checks=checks)
        obs_events.record("rollback", stage="gate", action="begin",
                          gen=cand.gen_id, incumbent=incumbent,
                          metric=failing["name"], reason=reason)
        with self._lock:
            self._phase = "rolling_back"
            for sid in set(self._swapped) | set(self._canary_ids):
                self._pending[sid] = (incumbent, "rollback")
        self._step_rolling_back()

    def _step_rolling_back(self) -> None:
        with self._lock:
            done = not self._pending and not self._swapped
        if done:
            self._finish_rollback()

    def _finish_rollback(self) -> None:
        with self._lock:
            cand = self._candidate
            incumbent = self._incumbent
            reason = self._fail_reason
        self._ledger.mark_failed(rollout_unit(cand.gen_id),
                                 error=reason or "demoted",
                                 phase="rolled_back", incumbent=incumbent)
        obs_events.record("rollback", stage="rollout", action="done",
                          gen=cand.gen_id, incumbent=incumbent, reason=reason)
        self._trace = obs_trace.span("promote_swap", self._trace,
                                     gen=cand.gen_id, action="rollback")
        self._reset_to_idle()

    def _reset_to_idle(self) -> None:
        with self._lock:
            self._phase = "idle"
            self._candidate = None
            self._pending = {}
            self._swapped = set()
            self._canary_ids = set()
            self._canary_blocks = 0
            self._fail_reason = None
            self._trace = None

    # -- crash resume ----------------------------------------------------------
    def _resume(self) -> None:
        """Replay the rollout ledger: complete or roll back any rollout
        interrupted mid-flight.  ``ACTIVE`` is the arbiter — a crash after
        the pointer flip completes the promotion, a crash before it rolls
        back; either way every restarted session adopts ``ACTIVE`` and
        lands on exactly one intact generation (the chaos-leg contract).

        No reference counterpart (module docstring)."""
        active = self.store.active()
        for unit, rec in sorted(self._ledger.replay().items()):
            if not unit.startswith("rollout:") or rec["state"] != "in_flight":
                continue
            gen_id = unit.split(":", 1)[1]
            phase = (rec.get("attrs") or {}).get("phase")
            if phase == "promoting" and active == gen_id:
                self._ledger.mark_done(unit, phase="done", resumed=True)
                obs_registry.counter("model_promotions").inc()
                obs_events.record("promotion", stage="rollout",
                                  action="promoted", gen=gen_id, resumed=True)
            else:
                self._ledger.mark_failed(
                    unit, error=f"crash during {phase!r}; rolled back",
                    phase="rolled_back", resumed=True, incumbent=active)
                obs_events.record("rollback", stage="rollout", action="resume",
                                  gen=gen_id, incumbent=active,
                                  reason=f"crash during {phase!r}")
        if active is not None:
            obs_registry.gauge("weight_generation").set(
                self.store.get(active).serial)
