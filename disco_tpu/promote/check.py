"""``make promote-check`` — the live-model-promotion gate (fifteenth gate).

Proves the canary/gate/rollback ladder end to end, hermetically (CPU
backend forced by the Makefile, loopback sockets only, ONE jax process,
compile cache off, zero SIGKILLs):

1. **Demotion**: a worse-on-purpose candidate (zeroed CRNN parameters —
   constant 0.5 masks) is staged against a live incumbent; the controller
   canaries it onto a deterministic fraction of the model-mask sessions at
   an atomic block boundary, the harness (playing the external scorer)
   feeds canary SDR samples far below the incumbent's, the gate fails on
   ``canary_sdr_db`` and rolls every canary back at the same boundary.
   Every delivered frame of every session — through the swap AND the
   rollback — is **bit-exact** against the offline per-generation oracle
   (per-block :func:`~disco_tpu.promote.lane.block_masks` under each
   block's recorded generation, chained through ``streaming_tango``), the
   flight recorder dumps a ``demotion`` post-mortem naming the failing
   metric, and the rollout ledger lands ``failed`` with the same reason.
2. **Promotion**: a good candidate dropped into the controller's watch
   directory is auto-staged, canaried, passes the SDR + SLO gate, and is
   promoted to every model session; the store's ``ACTIVE`` pointer flips
   atomically, ``model_promotions``/``weight_generation``/
   ``tap_to_promotion_ms`` are recorded, and both sessions' full streams
   stay bit-exact against their mixed-generation oracles.
3. **Chaos (pre_swap)**: a :class:`~disco_tpu.runs.chaos.ChaosCrash` at
   the dispatch thread's ``pre_swap`` seam kills the whole server
   mid-rollout — after one canary already swapped and checkpointed, before
   the second could.  No torn weight file (every generation still
   digest-verifies), no torn session checkpoint, ``ACTIVE`` still the
   incumbent, and the rollout unit still ``in_flight``.  A restarted
   server resolves the interrupted rollout to ``failed`` from the ledger,
   resumes the checkpointed session bit-exact on the incumbent, and then
   promotes a fresh candidate cleanly — resumability, not just survival.
4. **Chaos (controller)**: ``mid_canary`` and ``post_gate`` crashes kill
   the controller thread alone — the server keeps serving bit-exact on
   whatever generation each session holds, the crash is surfaced like a
   dispatch-thread death (``PromotionController.crashed``), and a fresh
   controller's ledger replay rolls the orphaned rollout back.

No reference counterpart: the reference trains once to a bare file and
serves nothing (SURVEY.md §5.1) — there is no rollout to drill.
"""
from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

K, C, U = 4, 2, 4
BLOCK = 2 * U
WIN = BLOCK // 2
WINDOW = 4           # canary window (blocks) for the gated legs
LONG, SHORT = 49152, 32000   # clip lengths: 24 / 15 paced blocks


def _scene(seed, L=LONG):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    T = Y.shape[-1] - (Y.shape[-1] % BLOCK)   # whole blocks only
    return Y[..., :T]


def _offline(Y, m):
    import numpy as np

    from disco_tpu.enhance.streaming import streaming_tango

    return np.asarray(
        streaming_tango(Y, m, m, update_every=U, policy="local")["yf"])


def _config(F):
    from disco_tpu.serve import SessionConfig

    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                         block_frames=BLOCK, update_every=U, masks="model")


def _arch(n_freq: int) -> dict:
    """The gate's tiny-CRNN build_crnn kwargs — small enough to jit in
    milliseconds, real enough to exercise the whole mask lane."""
    return dict(n_ch=1, win_len=WIN, n_freq=n_freq,
                cnn_filters=(4,), pool_kernels=((1, 4),),
                conv_padding=((0, 1),), rnn_units=(16,),
                ff_units=(n_freq,), rnn_dropouts=0.0)


def _seed_variables(arch: dict, seed: int) -> dict:
    import numpy as np

    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    model, tx = build_crnn(**arch)
    x0 = np.zeros((1, arch["n_ch"], WIN, arch["n_freq"]), np.float32)
    state = create_train_state(model, tx, x0, seed=seed)
    return {"params": state.params, "batch_stats": state.batch_stats}


def _perturb(variables: dict, eps: float) -> dict:
    """A 'good candidate': the incumbent nudged by eps — different digest,
    comparable numbers."""
    import jax

    params = jax.tree_util.tree_map(
        lambda a: (a + eps).astype(a.dtype), variables["params"])
    return {"params": params, "batch_stats": variables["batch_stats"]}


def _zeroed(variables: dict) -> dict:
    """The worse-on-purpose candidate: zeroed parameters — every mask
    collapses to sigmoid(0) = 0.5."""
    import jax

    params = jax.tree_util.tree_map(
        lambda a: (a * 0).astype(a.dtype), variables["params"])
    return {"params": params, "batch_stats": variables["batch_stats"]}


def _rollout_rec(store, gen_id):
    from disco_tpu.promote.controller import rollout_unit

    return store.rollout_ledger().replay().get(rollout_unit(gen_id))


def _round(clients, clips, cursors, delivered, score=None):
    """One paced round: every client sends its next block and waits for the
    delivery — block-boundary pacing, so generation swaps land between
    rounds and every block runs under exactly one generation."""
    for j, (cl, Yc) in enumerate(zip(clients, clips)):
        i = cursors[j]
        lo = i * BLOCK
        cl.send_block(Yc[..., lo:lo + BLOCK])
        delivered[j][i] = cl.recv_enhanced(i, timeout_s=120)
        cursors[j] = i + 1
        if score is not None:
            score(j, i, cl.gen_of.get(i))


def _gen_oracle(Y, gens, store):
    """The offline replay oracle: per-block masks under each block's
    recorded generation (store-loaded, digest-verified weights — loading
    doubles as the no-torn-file check), chained through the same
    streaming_tango carry the server runs."""
    import numpy as np

    from disco_tpu.promote.lane import block_masks
    from disco_tpu.promote.store import model_for_arch

    cache: dict = {}
    ms = []
    for i, g in enumerate(gens):
        if g not in cache:
            gen = store.get(g)
            cache[g] = (model_for_arch(gen.arch), store.load(g)[1])
        model, variables = cache[g]
        lo = i * BLOCK
        ms.append(block_masks(Y[..., lo:lo + BLOCK], model, variables))
    m = np.concatenate(ms, axis=-1)
    return _offline(Y[..., :len(gens) * BLOCK], m)


def _assert_stream(failures, label, delivered, gen_of, Y, store,
                   want_gens=None):
    """Stitch one session's delivered frames and compare bit-for-bit
    against its per-generation oracle; returns the per-block generation
    list."""
    import numpy as np

    n = max(delivered) + 1 if delivered else 0
    if sorted(delivered) != list(range(n)):
        failures.append(f"{label}: delivered seqs have holes "
                        f"({sorted(delivered)})")
        return []
    gens = [gen_of.get(i) for i in range(n)]
    if None in gens:
        failures.append(f"{label}: enhanced frames missing generation tags "
                        f"at seqs {[i for i, g in enumerate(gens) if g is None]}")
        return gens
    if want_gens is not None and set(gens) != set(want_gens):
        failures.append(f"{label}: generations {sorted(set(gens))} delivered, "
                        f"expected exactly {sorted(set(want_gens))}")
    got = np.concatenate([delivered[i] for i in range(n)], axis=-1)
    ref = _gen_oracle(Y, gens, store)
    if not np.array_equal(got, ref):
        failures.append(
            f"{label}: stream not bit-exact vs the per-generation offline "
            f"oracle (max abs diff {np.abs(got - ref).max():g})")
    return gens


def _check_rollback(failures: list, tmp: Path) -> dict:
    """Experiment 1: worse candidate → canary → SDR gate fails → rollback,
    bit-exact throughout, flight dump names the metric."""
    from disco_tpu.obs import flight as obs_flight
    from disco_tpu.promote.controller import PromotionController
    from disco_tpu.promote.store import GenerationStore
    from disco_tpu.serve import EnhanceServer, ServeClient

    clips = [_scene(71), _scene(72)]
    F = clips[0].shape[-2]
    n_blocks = clips[0].shape[-1] // BLOCK
    store = GenerationStore(tmp / "p1")
    arch = _arch(F)
    vars_a = _seed_variables(arch, seed=1)
    inc = store.stage_variables(vars_a, arch=arch, source="check-incumbent")
    store.set_active(inc.gen_id)

    flight_dir = tmp / "p1_flight"
    obs_flight.enable(dump_dir=flight_dir, capacity=64)
    ctl = PromotionController(store, canary_frac=0.5, sdr_gate_db=1.0,
                              slo_gate=True, window_blocks=WINDOW,
                              min_scores=2, gate_timeout_s=60.0, poll_s=0.01)
    srv = EnhanceServer(max_sessions=4, promote=ctl)
    cand_id = [None]

    def score(j, i, gen):
        # the harness plays the external scorer (offer_score is the serve
        # API for it): the bad candidate's blocks measure far below the
        # incumbent baseline
        ctl.offer_score(f"m{j}", i, 2.0 if gen == cand_id[0] else 10.0,
                        gen=gen)

    try:
        addr = srv.start()
        clients = []
        for j in range(2):
            cl = ServeClient(addr)
            cl.open(_config(F), session_id=f"m{j}")
            clients.append(cl)
        delivered = [{}, {}]
        cursors = [0, 0]
        for _ in range(2):                      # incumbent warm-up
            _round(clients, clips, cursors, delivered, score)
        cand = store.stage_variables(_zeroed(vars_a), arch=arch,
                                     source="check-bad")
        cand_id[0] = cand.gen_id
        while cursors[0] < n_blocks - 2:
            rec = _rollout_rec(store, cand.gen_id)
            if rec is not None and rec["state"] == "failed":
                break
            _round(clients, clips, cursors, delivered, score)
        for _ in range(2):                      # post-rollback service
            _round(clients, clips, cursors, delivered, score)
        for cl in clients:
            cl.close()
            cl.shutdown()
        srv.stop(timeout_s=120)
    finally:
        obs_flight.disable()

    rec = _rollout_rec(store, cand.gen_id)
    if rec is None or rec["state"] != "failed":
        failures.append(
            f"rollback: bad candidate's rollout never resolved to failed "
            f"within {cursors[0]} paced blocks "
            f"(ledger: {None if rec is None else rec['state']})")
    else:
        attrs = rec.get("attrs") or {}
        err = str(attrs.get("error", ""))
        if "canary_sdr_db" not in err:
            failures.append(
                f"rollback: ledger failure reason {err!r} does not name the "
                "failing metric canary_sdr_db")
    if store.active() != inc.gen_id:
        failures.append(
            f"rollback: ACTIVE moved to {store.active()} — a demoted "
            "candidate must never take the pointer")
    dumps = sorted(flight_dir.glob("flight-*-demotion.json"))
    if not dumps:
        failures.append("rollback: no demotion flight dump was written")
    elif "canary_sdr_db" not in dumps[-1].read_text():
        failures.append(f"rollback: demotion dump {dumps[-1].name} does not "
                        "name the failing metric")

    gens0 = _assert_stream(failures, "rollback canary m0", delivered[0],
                           clients[0].gen_of, clips[0], store,
                           want_gens={inc.gen_id, cand.gen_id})
    _assert_stream(failures, "rollback bystander m1", delivered[1],
                   clients[1].gen_of, clips[1], store,
                   want_gens={inc.gen_id})
    # the canary's history must be exactly incumbent → candidate →
    # incumbent: one swap in, one swap back, both at block boundaries
    if gens0:
        flips = [i for i in range(1, len(gens0)) if gens0[i] != gens0[i - 1]]
        if len(flips) != 2 or gens0[0] != inc.gen_id or gens0[-1] != inc.gen_id:
            failures.append(
                f"rollback: canary generation history has {len(flips)} "
                f"transitions ({gens0}) — expected incumbent → candidate → "
                "incumbent")
    return {"blocks": cursors[0], "candidate": cand.gen_id,
            "dumps": len(dumps)}


def _check_promote(failures: list, tmp: Path) -> dict:
    """Experiment 2: a good candidate from the watch dir auto-stages,
    passes the gate and promotes to every session."""
    from flax import serialization

    from disco_tpu.io.atomic import write_bytes_atomic
    from disco_tpu.obs.metrics import REGISTRY as obs_registry
    from disco_tpu.promote.controller import PromotionController
    from disco_tpu.promote.store import GenerationStore
    from disco_tpu.serve import EnhanceServer, ServeClient

    clips = [_scene(81), _scene(82)]
    F = clips[0].shape[-2]
    n_blocks = clips[0].shape[-1] // BLOCK
    store = GenerationStore(tmp / "p2")
    watch = tmp / "p2_incoming"
    watch.mkdir()
    arch = _arch(F)
    vars_a = _seed_variables(arch, seed=2)
    inc = store.stage_variables(vars_a, arch=arch, source="check-incumbent")
    store.set_active(inc.gen_id)

    ctl = PromotionController(store, canary_frac=0.5, sdr_gate_db=1.0,
                              slo_gate=True, window_blocks=WINDOW,
                              min_scores=2, gate_timeout_s=60.0, poll_s=0.01,
                              watch_dir=watch)
    srv = EnhanceServer(max_sessions=4, promote=ctl)
    cand_id = [None]

    def score(j, i, gen):
        ctl.offer_score(f"m{j}", i, 10.5 if gen == cand_id[0] else 10.0,
                        gen=gen)

    promotions0 = obs_registry.peek_counter("model_promotions")
    addr = srv.start()
    clients = []
    for j in range(2):
        cl = ServeClient(addr)
        cl.open(_config(F), session_id=f"m{j}")
        clients.append(cl)
    delivered = [{}, {}]
    cursors = [0, 0]
    for _ in range(2):
        _round(clients, clips, cursors, delivered, score)
    # the publish seam the CLI trainer uses: a finished checkpoint dropped
    # into the watch dir is staged by the controller itself
    cand_vars = _perturb(vars_a, 1e-3)
    blob = serialization.msgpack_serialize(serialization.to_state_dict(
        {"params": cand_vars["params"],
         "batch_stats": cand_vars["batch_stats"]}))
    write_bytes_atomic(watch / "candidate.msgpack", blob)
    deadline = time.monotonic() + 10.0
    while len(store.list_ids()) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    staged = [g for g in store.list_ids() if g != inc.gen_id]
    if not staged:
        failures.append("promote: the watch-dir candidate was never staged")
        srv.stop(timeout_s=120)
        return {"blocks": cursors[0]}
    cand_id[0] = staged[0]
    while cursors[0] < n_blocks - 2:
        rec = _rollout_rec(store, cand_id[0])
        if rec is not None and rec["state"] == "done":
            break
        _round(clients, clips, cursors, delivered, score)
    for _ in range(2):                          # post-promotion service
        _round(clients, clips, cursors, delivered, score)
    for cl in clients:
        cl.close()
        cl.shutdown()
    srv.stop(timeout_s=120)

    rec = _rollout_rec(store, cand_id[0])
    if rec is None or rec["state"] != "done":
        failures.append(
            f"promote: good candidate's rollout never resolved to done "
            f"within {cursors[0]} paced blocks "
            f"(ledger: {None if rec is None else rec['state']})")
    if store.active() != cand_id[0]:
        failures.append(
            f"promote: ACTIVE is {store.active()}, expected the promoted "
            f"candidate {cand_id[0]}")
    promoted = obs_registry.peek_counter("model_promotions") - promotions0
    if promoted != 1:
        failures.append(
            f"promote: model_promotions counter moved by {promoted}, "
            "expected 1")
    snap = obs_registry.snapshot()
    if snap["gauges"].get("weight_generation") != 2:
        failures.append(
            f"promote: weight_generation gauge is "
            f"{snap['gauges'].get('weight_generation')}, expected the "
            "candidate's serial 2")
    if not (snap["histograms"].get("tap_to_promotion_ms") or {}).get("count"):
        failures.append("promote: tap_to_promotion_ms histogram was never "
                        "observed")
    for j in range(2):
        gens = _assert_stream(failures, f"promote m{j}", delivered[j],
                              clients[j].gen_of, clips[j], store,
                              want_gens={inc.gen_id, cand_id[0]})
        if gens and gens[-1] != cand_id[0]:
            failures.append(f"promote: m{j} ended on {gens[-1]}, not the "
                            "promoted candidate")
    return {"blocks": cursors[0], "candidate": cand_id[0]}


def _check_chaos_pre_swap(failures: list, tmp: Path) -> dict:
    """Experiment 3: ChaosCrash at the pre_swap seam mid-rollout — the
    whole server dies with one canary swapped+checkpointed and one not;
    restart resumes from the ledger with zero torn state."""
    import numpy as np

    from disco_tpu.io.atomic import TMP_SUFFIX
    from disco_tpu.promote.controller import PromotionController
    from disco_tpu.promote.store import GenerationStore, PublishRefused
    from disco_tpu.runs import chaos
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError
    from disco_tpu.serve.session import probe_session_state

    clips = [_scene(91), _scene(92)]
    F = clips[0].shape[-2]
    n_blocks = clips[0].shape[-1] // BLOCK
    root, state_dir = tmp / "p3", tmp / "p3_state"
    store = GenerationStore(root)
    arch = _arch(F)
    vars_a = _seed_variables(arch, seed=3)
    inc = store.stage_variables(vars_a, arch=arch, source="check-incumbent")
    store.set_active(inc.gen_id)

    def controller():
        return PromotionController(store, canary_frac=1.0, sdr_gate_db=None,
                                   slo_gate=True, window_blocks=2,
                                   gate_timeout_s=60.0, poll_s=0.01)

    srv = EnhanceServer(max_sessions=4, promote=controller(),
                        state_dir=state_dir)
    addr = srv.start()
    clients = []
    for j in range(2):
        cl = ServeClient(addr)
        cl.open(_config(F), session_id=f"m{j}")
        clients.append(cl)
    delivered = [{}, {}]
    cursors = [0, 0]
    for _ in range(2):
        _round(clients, clips, cursors, delivered)
    # with canary_frac=1.0 BOTH sessions get canary swap requests; the
    # dispatch thread applies them in one tick — the first checkpoint+swap
    # succeeds, the second hit dies like a process death mid-rollout
    chaos.configure("pre_swap", after=2)
    crashes = 0
    cand = store.stage_variables(_perturb(vars_a, 2e-3), arch=arch,
                                 source="check-crashee")
    try:
        while cursors[0] < n_blocks:
            _round(clients, clips, cursors, delivered)
        failures.append("chaos: pre_swap crash never fired")
    except ServeError:
        pass                  # the connection died with the server
    finally:
        chaos.disable()
    try:
        srv.wait(timeout_s=60)
        failures.append("chaos: dispatch thread survived the pre_swap crash")
    except chaos.ChaosCrash:
        crashes += 1
    for cl in clients:
        cl.shutdown()

    # zero torn state: pointer, weight files, checkpoints, ledger
    if store.active() != inc.gen_id:
        failures.append(f"chaos: ACTIVE moved to {store.active()} through a "
                        "mid-rollout crash")
    for gen_id in store.list_ids():
        try:
            store.load(gen_id)
        except PublishRefused as e:
            failures.append(f"chaos: generation {gen_id} torn after the "
                            f"crash: {e}")
    litter = [str(p) for d in (root, state_dir) if d.is_dir()
              for p in d.rglob(f"*{TMP_SUFFIX}.*")]
    if litter:
        failures.append(f"chaos: atomic-write temp litter after the crash: "
                        f"{litter}")
    rec = _rollout_rec(store, cand.gen_id)
    if rec is None or rec["state"] != "in_flight":
        failures.append(
            f"chaos: interrupted rollout is {None if rec is None else rec['state']!r} "
            "in the ledger, expected in_flight (crash truth)")
    ckpt = state_dir / "session_m0.state.msgpack"
    if not ckpt.is_file() or not probe_session_state(ckpt):
        failures.append("chaos: the swapped canary's boundary checkpoint is "
                        "missing or fails its probe")

    # restart: the resume settles the rollout, the checkpointed session
    # reattaches on the incumbent, and a FRESH candidate still promotes
    srv2 = EnhanceServer(max_sessions=4, promote=controller(),
                         state_dir=state_dir)
    addr2 = srv2.start()
    rec = _rollout_rec(store, cand.gen_id)
    if rec is None or rec["state"] != "failed":
        failures.append(
            f"chaos: restart left the interrupted rollout "
            f"{None if rec is None else rec['state']!r}, expected failed "
            "(rolled back from the ledger)")
    cl = ServeClient(addr2)
    cl.open(_config(F), resume="m0")
    k = len(delivered[0])
    if cl.blocks_done != k:
        failures.append(f"chaos: resume landed at blocks_done="
                        f"{cl.blocks_done}, expected {k} — the boundary "
                        "checkpoint and the delivered stream disagree")
        k = cl.blocks_done
    cursors2 = [k]
    delivered2 = [dict(delivered[0])]
    for _ in range(2):
        _round([cl], clips[:1], cursors2, delivered2)
    cand2 = store.stage_variables(_perturb(vars_a, 3e-3), arch=arch,
                                  source="check-post-crash")
    while cursors2[0] < n_blocks - 2:
        rec2 = _rollout_rec(store, cand2.gen_id)
        if rec2 is not None and rec2["state"] == "done":
            break
        _round([cl], clips[:1], cursors2, delivered2)
    for _ in range(2):
        _round([cl], clips[:1], cursors2, delivered2)
    cl.close()
    cl.shutdown()
    srv2.stop(timeout_s=120)
    rec2 = _rollout_rec(store, cand2.gen_id)
    if rec2 is None or rec2["state"] != "done":
        failures.append(
            "chaos: the post-restart candidate never promoted — the rollout "
            f"machine did not survive the crash (ledger: "
            f"{None if rec2 is None else rec2['state']})")
    if store.active() != cand2.gen_id:
        failures.append(f"chaos: post-restart ACTIVE is {store.active()}, "
                        f"expected {cand2.gen_id}")

    # every pre-crash frame ran under the incumbent (the crash fired before
    # any candidate block could dispatch), and the stitched pre-crash +
    # resumed stream is bit-exact vs the per-generation oracle
    pre_gens = {g for cl_ in clients for g in cl_.gen_of.values()}
    if pre_gens - {inc.gen_id}:
        failures.append(
            f"chaos: pre-crash frames tagged {sorted(pre_gens)} — blocks ran "
            "under a generation the crash should have kept off the stream")
    gen_of = dict(clients[0].gen_of)
    gen_of.update(cl.gen_of)
    _assert_stream(failures, "chaos resumed m0", delivered2[0], gen_of,
                   clips[0], store, want_gens={inc.gen_id, cand2.gen_id})
    n1 = len(delivered[1])
    if n1:
        got = np.concatenate([delivered[1][i] for i in range(n1)], axis=-1)
        ref = _gen_oracle(clips[1], [inc.gen_id] * n1, store)
        if not np.array_equal(got, ref):
            failures.append(
                "chaos: the unswapped session's pre-crash frames are not "
                f"bit-exact (max abs diff {np.abs(got - ref).max():g})")
    return {"crashes_injected": crashes, "blocks_before_crash": k,
            "blocks_total": cursors2[0]}


def _check_controller_crash(failures: list, tmp: Path) -> dict:
    """Experiment 4: mid_canary / post_gate crashes kill the controller
    thread only — the server keeps serving, the ledger replay rolls the
    orphaned rollout back."""
    from disco_tpu.promote.controller import PromotionController
    from disco_tpu.promote.store import GenerationStore
    from disco_tpu.runs import chaos
    from disco_tpu.serve import EnhanceServer, ServeClient

    clip = _scene(95, L=SHORT)
    F = clip.shape[-2]
    n_blocks = clip.shape[-1] // BLOCK
    store = GenerationStore(tmp / "p4")
    arch = _arch(F)
    vars_a = _seed_variables(arch, seed=4)
    inc = store.stage_variables(vars_a, arch=arch, source="check-incumbent")
    store.set_active(inc.gen_id)

    ctl = PromotionController(store, canary_frac=1.0, sdr_gate_db=None,
                              slo_gate=False, window_blocks=2,
                              gate_timeout_s=30.0, poll_s=0.01)
    srv = EnhanceServer(max_sessions=4, promote=ctl)
    addr = srv.start()
    cl = ServeClient(addr)
    cl.open(_config(F), session_id="m0")
    delivered = [{}]
    cursors = [0]
    _round([cl], [clip], cursors, delivered)
    crashes = 0
    chaos.configure("mid_canary", after=1)
    cand = store.stage_variables(_perturb(vars_a, 4e-3), arch=arch,
                                 source="check-mid-canary")
    try:
        while ctl.crashed is None and cursors[0] < n_blocks - 3:
            _round([cl], [clip], cursors, delivered)
    finally:
        chaos.disable()
    if not isinstance(ctl.crashed, chaos.ChaosCrash):
        failures.append("controller: mid_canary crash never fired "
                        f"(crashed={ctl.crashed!r})")
    else:
        crashes += 1
    # the serve process must keep delivering on the generations the
    # sessions already hold — a dead controller degrades, never corrupts
    for _ in range(3):
        _round([cl], [clip], cursors, delivered)
    cl.close()
    cl.shutdown()
    srv.stop(timeout_s=120)
    _assert_stream(failures, "controller-crash m0", delivered[0], cl.gen_of,
                   clip, store, want_gens={inc.gen_id, cand.gen_id})
    rec = _rollout_rec(store, cand.gen_id)
    if rec is None or rec["state"] != "in_flight":
        failures.append(
            f"controller: orphaned rollout is "
            f"{None if rec is None else rec['state']!r}, expected in_flight")
    ctl_r = PromotionController(store, poll_s=0.01)
    ctl_r.start()
    ctl_r.stop()
    ctl_r.wait()
    rec = _rollout_rec(store, cand.gen_id)
    if rec is None or rec["state"] != "failed":
        failures.append("controller: ledger replay did not roll the "
                        "mid_canary rollout back")
    if store.active() != inc.gen_id:
        failures.append(f"controller: ACTIVE is {store.active()} after the "
                        "mid_canary drill, expected the incumbent")

    # post_gate: the verdict is reached (the zero-traffic timeout demotes)
    # but the crash lands before the ledger goes final
    chaos.configure("post_gate", after=1)
    cand2 = store.stage_variables(_perturb(vars_a, 5e-3), arch=arch,
                                  source="check-post-gate")
    ctl_p = PromotionController(store, canary_frac=1.0, sdr_gate_db=None,
                                slo_gate=False, window_blocks=2,
                                gate_timeout_s=0.2, poll_s=0.01)
    try:
        ctl_p.start()
        deadline = time.monotonic() + 10.0
        while ctl_p.crashed is None and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        chaos.disable()
        ctl_p.stop()
        ctl_p.wait()
    if not isinstance(ctl_p.crashed, chaos.ChaosCrash):
        failures.append("controller: post_gate crash never fired "
                        f"(crashed={ctl_p.crashed!r})")
    else:
        crashes += 1
    rec = _rollout_rec(store, cand2.gen_id)
    if rec is None or rec["state"] != "in_flight":
        failures.append(
            f"controller: post_gate rollout is "
            f"{None if rec is None else rec['state']!r} at the crash, "
            "expected in_flight (verdict reached, ledger not final)")
    ctl_r2 = PromotionController(store, poll_s=0.01)
    ctl_r2.start()
    ctl_r2.stop()
    ctl_r2.wait()
    rec = _rollout_rec(store, cand2.gen_id)
    if rec is None or rec["state"] != "failed":
        failures.append("controller: ledger replay did not roll the "
                        "post_gate rollout back")
    return {"crashes_injected": crashes, "blocks": cursors[0]}


def main(argv=None) -> int:
    """Run the promotion gate (``make promote-check``); exit 1 on failure.

    No reference counterpart (module docstring)."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obs_log = tmp / "promote_check.jsonl"
        with obs.recording(obs_log):
            obs.write_manifest(tool="promote-check")
            rollback = _check_rollback(failures, tmp)
            promote = _check_promote(failures, tmp)
            chaos_stats = _check_chaos_pre_swap(failures, tmp)
            ctl_stats = _check_controller_crash(failures, tmp)
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(obs_log)   # schema-validating read

        def count(kind, action):
            return sum(1 for e in events if e["kind"] == kind
                       and e["attrs"].get("action") == action)

        if not count("promotion", "staged"):
            failures.append("event log missing the watch-dir staged event")
        if count("promotion", "promoted") < 2:
            failures.append("event log missing promoted events (clean + "
                            "post-crash promotion)")
        if not count("canary", "assign") or not count("canary", "swap"):
            failures.append("event log missing canary assign/swap events")
        if not count("rollback", "begin") or not count("rollback", "done"):
            failures.append("event log missing the demotion begin/done events")
        if count("rollback", "resume") < 1:
            failures.append("event log missing the crash-resume rollback "
                            "event")
        if count("rollback", "crashed") != 2:
            failures.append(
                f"event log carries {count('rollback', 'crashed')} "
                "controller-crash events, expected 2 (mid_canary + post_gate)")
        crashes = (chaos_stats["crashes_injected"]
                   + ctl_stats["crashes_injected"])
        chaos_events = [e for e in events if e["kind"] == "fault"
                        and e["attrs"].get("fault") == "chaos_crash"]
        if len(chaos_events) != crashes:
            failures.append(
                f"event log carries {len(chaos_events)} chaos_crash events, "
                f"expected {crashes}")

    if failures:
        for f in failures:
            print(f"promote-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "promote_check": "ok",
        "rollback_blocks": rollback["blocks"],
        "promote_blocks": promote["blocks"],
        "canary_window": WINDOW,
        "blocks_before_crash": chaos_stats["blocks_before_crash"],
        "crashes_injected": crashes,
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
