"""One typed configuration tree for the whole framework.

Replaces the reference's three config mechanisms — per-CLI argparse,
hard-coded module constants, and ad-hoc YAML (SURVEY.md §5.6) — with a
single dataclass hierarchy.  Every default below is a canonical value from
the reference (citations inline); the CLIs parse flags *into* this tree and
all library code reads *from* it, so there is exactly one place where
"4 nodes x 4 mics, 512/256 STFT, SNR in [0, 6]" lives.

YAML round-trip: :func:`load_config` / :func:`save_config` use plain
``yaml.safe_*`` over nested dicts; the reference's space-separated-int
string convention is honored via :func:`disco_tpu.core.miscx.integerize`.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path

import yaml

from disco_tpu.sim.defaults import RoomDefaults, SignalDefaults


@dataclasses.dataclass(frozen=True)
class StftConfig:
    """Reference tango.py:28-29, post_generator.py:27-28."""

    n_fft: int = 512
    hop: int = 256
    fs: int = 16000

    @property
    def n_freq(self) -> int:
        return self.n_fft // 2 + 1


@dataclasses.dataclass(frozen=True)
class ArrayConfig:
    """The 4-node x 4-mic circular WASN geometry (tango.py:30-32,
    convolve_signals.py:362-363)."""

    mics_per_node: tuple = (4, 4, 4, 4)
    ref_mics: tuple = (0, 0, 0, 0)
    radius_m: float = 0.05

    @property
    def n_nodes(self) -> int:
        return len(self.mics_per_node)

    @property
    def n_channels(self) -> int:
        return int(sum(self.mics_per_node))


@dataclasses.dataclass(frozen=True)
class EnhanceConfig:
    """TANGO inference constants (tango.py:33-38, speech_enhancement/utils.py:7-10)."""

    win_len: int = 21
    pred_frame: str = "mid"  # 'first' | 'mid' | 'last'
    snr_range: tuple = ((0, 6),)
    mu: float = 1.0
    filter_type: str = "gevd"
    rank: int = 1
    # rank-1 GEVD solver spec: 'eigh' | 'power' | 'power:N' | 'jacobi' |
    # 'jacobi-pallas' | 'fused' | 'fused-xla' | 'fused-pallas' (all with
    # optional ':N'; beam.filters.rank1_gevd).  The TANGO CLI resolves
    # its solver as: explicit --solver > enhance.solver from a --config
    # YAML > this default (cli/tango.py main()).
    #
    # Default 'power': measured on-device (round-3 solver_ab,
    # exp/tpu_validation_r3.jsonl) at 6722x RTF vs eigh's 4833x (+39%)
    # with 49 dB output agreement and <=0.1 dB pinned SDR delta.  Pass
    # 'eigh' for bit-level reference-matching validation runs; 'jacobi'
    # is kept as the streaming-refresh candidate (it is measured SLOWER
    # than eigh offline: 3447x).
    solver: str = "power"
    stft_clip: tuple = (1e-6, 1e3)
    frames_lost: int = 6  # conv-cropped frames of the CRNN (utils.py:10)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """CRNN training hyperparameters (train.py:66-85, crnn.py:105,
    datasets.py:6-9)."""

    archi: str = "crnn"
    batch_size: int = 500
    epochs: int = 150
    lr: float = 1e-3
    optimizer: str = "rmsprop"
    win_len: int = 21
    win_hop: int = 8
    val_split: float = 0.0909
    output_frames: str = "all"
    grad_clip: float | None = None
    train_dur_s: float = 11.0
    early_stop_patience: int = 10
    # CRNN architecture (dnn/utils.py:145-151)
    filters: tuple = (32, 64, 64)
    kernel: tuple = (3, 3)
    pool: tuple = (1, 4)
    rnn_units: int = 256
    ff_units: int = 257


@dataclasses.dataclass(frozen=True)
class CorpusConfig:
    """Corpus shape (tango.py:43-45, post_generator.py:49-50)."""

    n_train: int = 10000
    n_val: int = 1000
    n_test: int = 1000
    scenario: str = "living"
    noise: str = "ssn"

    @property
    def splits(self) -> tuple:
        return (self.n_train, self.n_val, self.n_test)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """TPU mesh axes for the node-sharded pipeline (SURVEY.md §2.9)."""

    n_node: int | None = None  # None -> all local devices
    n_batch: int = 1
    n_frame: int = 1  # sequence-parallel frame-axis shards


@dataclasses.dataclass(frozen=True)
class DiscoConfig:
    """The root of the tree."""

    root: str = "dataset"
    stft: StftConfig = StftConfig()
    array: ArrayConfig = ArrayConfig()
    enhance: EnhanceConfig = EnhanceConfig()
    train: TrainConfig = TrainConfig()
    corpus: CorpusConfig = CorpusConfig()
    mesh: MeshConfig = MeshConfig()
    room: RoomDefaults = RoomDefaults()
    signal: SignalDefaults = SignalDefaults()


_SECTIONS = {
    "stft": StftConfig,
    "array": ArrayConfig,
    "enhance": EnhanceConfig,
    "train": TrainConfig,
    "corpus": CorpusConfig,
    "mesh": MeshConfig,
    "room": RoomDefaults,
    "signal": SignalDefaults,
}


def _to_plain(obj):
    """Dataclass tree -> YAML-safe nested dict (tuples become lists)."""
    if dataclasses.is_dataclass(obj):
        return {f.name: _to_plain(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, (tuple, list)):
        return [_to_plain(v) for v in obj]
    if hasattr(obj, "item"):  # numpy scalar
        return obj.item()
    return obj


def _tuplify(v):
    return tuple(_tuplify(x) for x in v) if isinstance(v, list) else v


def config_from_dict(d: dict) -> DiscoConfig:
    """Build a :class:`DiscoConfig` from a nested dict, applying defaults for
    anything absent and tuplifying lists (YAML has no tuples)."""
    kwargs = {}
    for name, section in d.items():
        if name in _SECTIONS:
            cls = _SECTIONS[name]
            valid = {f.name for f in dataclasses.fields(cls)}
            unknown = set(section) - valid
            if unknown:
                raise ValueError(f"unknown keys in config section {name!r}: {sorted(unknown)}")
            kwargs[name] = cls(**{k: _tuplify(v) for k, v in section.items()})
        elif name == "root":
            kwargs["root"] = section
        else:
            raise ValueError(f"unknown config section {name!r}")
    return DiscoConfig(**kwargs)


def load_config(path) -> DiscoConfig:
    """Load a YAML file into a config (via :func:`config_from_dict`)."""
    with open(path) as fh:
        return config_from_dict(yaml.safe_load(fh) or {})


def save_config(cfg: DiscoConfig, path) -> Path:
    """Write the config back to YAML at ``path`` (inverse of :func:`load_config`)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as fh:
        yaml.safe_dump(_to_plain(cfg), fh, sort_keys=False)
    return path
